"""Flight recorder — the always-on bounded black box.

When a run dies — :class:`~autodist_tpu.runtime.sentinel.TrainingDiverged`,
a circuit-breaker trip, a fatal signal — the postmortem question is
always the same: *what was this process doing just before?* The trace
ring buffer answers it only if tracing was on and only until the process
is gone. The flight recorder is the crash-safe complement: an always-on,
strictly bounded in-memory record of

- the last ``ADT_BLACKBOX_EVENTS`` **resilience/health events**
  (sentinel verdicts and rollbacks, breaker opens, retry exhaustion,
  degraded pulls — anything a subsystem ``record()``\\ s),
- the last N **log records** (a bounded logging handler on the
  framework logger),
- the **recent span tail** + current counters/gauges from the global
  recorder (with deltas against process start),

dumped **atomically** (tmp + ``os.replace``) as one JSON file under
``ADT_BLACKBOX_DIR`` on every trigger: ``TrainingDiverged``, sentinel
rollback, breaker-open, SIGTERM (when installable), or at exit when
``ADT_BLACKBOX_DUMP=1``. Old dumps are pruned to ``ADT_BLACKBOX_KEEP``.
Inspect with ``python -m autodist_tpu.telemetry blackbox <dump>``.

Recording cost is one deque append under a lock — safe on every hot
path; ``ADT_BLACKBOX=0`` disables dumps and the signal hook but keeps
``record()`` a cheap no-op-equivalent (events still collect; nothing is
written).
"""
import collections
import json
import logging as std_logging
import os
import threading
import time
from typing import Optional

from autodist_tpu import const
from autodist_tpu.utils import logging

_MAX_DUMP_SPANS = 512


class _BlackboxLogHandler(std_logging.Handler):
    """Bounded tail of formatted log lines (WARNING+ by default keeps
    the tail signal-dense; the level rides ``ADT_MIN_LOG_LEVEL``'s
    floor, never above WARNING)."""

    def __init__(self, ring: collections.deque):
        super().__init__(level=std_logging.WARNING)
        self._ring = ring

    def emit(self, record: std_logging.LogRecord):
        try:
            self._ring.append({"ts": round(record.created, 6),
                               "level": record.levelname,
                               "src": "%s:%d" % (record.filename,
                                                 record.lineno),
                               "msg": record.getMessage()})
        except Exception:  # noqa: BLE001 — the recorder must never raise
            pass


class FlightRecorder:
    """The bounded black box. One process-global instance
    (:func:`get_flight_recorder`); independent instances for tests."""

    def __init__(self, capacity_events: Optional[int] = None,
                 capacity_logs: int = 200):
        if capacity_events is None:
            capacity_events = max(int(const.ENV.ADT_BLACKBOX_EVENTS.val), 8)
        self._events: collections.deque = collections.deque(
            maxlen=capacity_events)
        self._logs: collections.deque = collections.deque(
            maxlen=capacity_logs)
        self._lock = threading.Lock()
        self._started_at = time.time()
        self._log_handler: Optional[_BlackboxLogHandler] = None
        self.dumps = 0
        self.last_dump_path: Optional[str] = None
        from autodist_tpu.telemetry import spans as spans_lib
        self._counters0 = spans_lib.counters()

    # ------------------------------------------------------------ record

    def record(self, kind: str, **data) -> None:
        """Append one event (wall-clock stamped). Values must be JSON-
        serializable scalars/strings — the dump coerces stragglers to
        ``repr``."""
        with self._lock:
            self._events.append((time.time(), kind, data))

    def attach_log_handler(self) -> None:
        """Tee the framework logger's WARNING+ tail into the box
        (idempotent)."""
        if self._log_handler is not None:
            return
        self._log_handler = _BlackboxLogHandler(self._logs)
        logging.get_logger().addHandler(self._log_handler)

    def detach_log_handler(self) -> None:
        if self._log_handler is not None:
            logging.get_logger().removeHandler(self._log_handler)
            self._log_handler = None

    # ---------------------------------------------------------- snapshot

    def snapshot(self, trigger: str) -> dict:
        """The dump payload: identity, trigger, events, span tail,
        registry state + deltas, log tail."""
        from autodist_tpu.telemetry import spans as spans_lib
        rec = spans_lib.get_recorder()
        counters = rec.counters()
        deltas = {k: v - self._counters0.get(k, 0.0)
                  for k, v in counters.items()
                  if v != self._counters0.get(k, 0.0)}
        epoch = getattr(rec, "epoch_offset_ns", 0)
        offset = getattr(rec, "clock_offset_ns", 0)
        spans_tail = [
            {"name": e.name, "cat": e.cat,
             "ts": round((e.ts_ns + epoch + offset) / 1e9, 6),
             "dur_ms": round(e.dur_ns / 1e6, 4), "tid": e.tid,
             "span_id": e.span_id, "args": _jsonable(e.args)}
            for e in rec.events()[-_MAX_DUMP_SPANS:]]
        with self._lock:
            events = [{"ts": round(ts, 6), "kind": kind,
                       "data": _jsonable(data)}
                      for ts, kind, data in self._events]
            logs = list(self._logs)
        return {
            "format": "adt-blackbox-v1",
            "trigger": trigger,
            "dumped_at": round(time.time(), 6),
            "started_at": round(self._started_at, 6),
            "host": rec.host, "pid": rec.pid,
            "worker": const.ENV.ADT_WORKER.val or "chief",
            "events": events,
            "spans": spans_tail,
            "dropped_spans": rec.dropped_events,
            "counters": counters,
            "counter_deltas": deltas,
            "gauges": rec.gauges(),
            "logs": logs,
        }

    # -------------------------------------------------------------- dump

    def dump(self, trigger: str,
             directory: Optional[str] = None) -> Optional[str]:
        """Atomically write one dump file; returns its path (None when
        ``ADT_BLACKBOX=0`` or the write failed — a black box must never
        take the process down with it)."""
        if not const.ENV.ADT_BLACKBOX.val:
            return None
        directory = directory or const.ENV.ADT_BLACKBOX_DIR.val
        try:
            os.makedirs(directory, exist_ok=True)
            from autodist_tpu.telemetry import spans as spans_lib
            rec = spans_lib.get_recorder()
            name = "blackbox-%s-%d-%d.json" % (
                time.strftime("%Y%m%d-%H%M%S"), rec.pid, self.dumps)
            path = os.path.join(directory, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.snapshot(trigger), f, default=repr)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self.dumps += 1
            self.last_dump_path = path
            spans_lib.counter_add("blackbox.dumps")
            logging.warning("flight recorder: dumped black box (%s) to %s",
                            trigger, path)
            self._prune(directory)
            return path
        except Exception as e:  # noqa: BLE001 — never fail the caller
            logging.warning("flight recorder: dump (%s) failed: %s",
                            trigger, e)
            return None

    @staticmethod
    def _prune(directory: str) -> None:
        keep = max(int(const.ENV.ADT_BLACKBOX_KEEP.val), 1)
        try:
            dumps = sorted(
                f for f in os.listdir(directory)
                if f.startswith("blackbox-") and f.endswith(".json"))
            for stale in dumps[:-keep]:
                os.remove(os.path.join(directory, stale))
        except OSError:
            pass

    def clear(self) -> None:
        """Drop events/logs and re-base counter deltas (test isolation)."""
        from autodist_tpu.telemetry import spans as spans_lib
        with self._lock:
            self._events.clear()
            self._logs.clear()
        self._counters0 = spans_lib.counters()


def _jsonable(data):
    if data is None:
        return None
    import math
    out = {}
    for k, v in dict(data).items():
        if isinstance(v, float) and not math.isfinite(v):
            # strict-JSON consumers reject bare NaN/Infinity tokens, and
            # a nan grad norm is exactly what a divergence dump carries
            out[k] = repr(v)
        elif isinstance(v, (int, float, str, bool)) or v is None:
            out[k] = v
        else:
            out[k] = repr(v)
    return out


# ------------------------------------------------------- module singleton

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()
_exit_hook_installed = False
_signal_hook_installed = False


def get_flight_recorder() -> FlightRecorder:
    """The process-global flight recorder (created on first use; log
    handler attached, exit/signal hooks installed per the env)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                fr = FlightRecorder()
                fr.attach_log_handler()
                _recorder = fr
                _install_hooks()
    return _recorder


def _install_hooks():
    global _exit_hook_installed, _signal_hook_installed
    if const.ENV.ADT_BLACKBOX_DUMP.val and not _exit_hook_installed:
        import atexit
        atexit.register(lambda: dump("exit (ADT_BLACKBOX_DUMP=1)"))
        _exit_hook_installed = True
    if (const.ENV.ADT_BLACKBOX.val and not _signal_hook_installed
            and threading.current_thread() is threading.main_thread()):
        _signal_hook_installed = True
        try:
            import signal

            prev = signal.getsignal(signal.SIGTERM)

            def _grace_active() -> bool:
                # the preemption plane consumed the SIGTERM as an advance
                # notice: the process lives through its grace window, so
                # the default-disposition re-raise must not fire
                try:
                    from autodist_tpu.runtime import preemption
                    return preemption.grace_active()
                except ImportError:
                    return False

            def _on_sigterm(signum, frame):
                # deterministic chain with the preemption notice handler
                # REGARDLESS of install order: the notice fires first,
                # the dump fires LAST (so its event tail contains the
                # notice). When the previous handler IS the notice
                # handler, run it before dumping; any other callable
                # keeps the legacy dump-then-chain order.
                notice_prev = (callable(prev)
                               and getattr(prev, "_adt_notice_handler",
                                           False))
                if notice_prev:
                    prev(signum, frame)
                record("signal", signum=signum)
                dump("fatal signal SIGTERM")
                if callable(prev) and not notice_prev:
                    prev(signum, frame)
                elif prev == signal.SIG_DFL and not _grace_active():
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            _on_sigterm._adt_blackbox_handler = True
            signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            pass  # non-main thread / restricted env: dumps still work


def record(kind: str, **data) -> None:
    """Module-level event append — THE instrumented-code entry point
    (sentinel verdicts, rollbacks, breaker opens, resilience events)."""
    get_flight_recorder().record(kind, **data)


def dump(trigger: str, directory: Optional[str] = None) -> Optional[str]:
    return get_flight_recorder().dump(trigger, directory=directory)


def reset() -> None:
    """Clear the box's events/logs (wired into ``autodist_tpu.reset()``
    for test isolation); hooks and the log handler stay installed."""
    if _recorder is not None:
        _recorder.clear()


# ---------------------------------------------------------------- loading


def load_dump(path: str) -> dict:
    with open(path) as f:
        d = json.load(f)
    if d.get("format") != "adt-blackbox-v1":
        raise ValueError("%s is not an adt-blackbox-v1 dump" % path)
    return d


def format_dump(d: dict, max_rows: int = 40) -> str:
    """Human-readable rendering of one dump (the CLI's ``blackbox``
    subcommand)."""
    lines = [
        "black box: trigger=%r worker=%s host=%s pid=%s"
        % (d.get("trigger"), d.get("worker"), d.get("host"), d.get("pid")),
        "  dumped_at=%s (up %.1fs)  spans=%d (+%d dropped)  dumps file "
        "format=%s"
        % (time.strftime("%Y-%m-%d %H:%M:%S",
                         time.localtime(d.get("dumped_at", 0))),
           d.get("dumped_at", 0) - d.get("started_at", 0),
           len(d.get("spans", [])), d.get("dropped_spans", 0),
           d.get("format")),
        "  events (%d, newest last):" % len(d.get("events", []))]
    for ev in d.get("events", [])[-max_rows:]:
        lines.append("    %s  %-24s %s"
                     % (time.strftime("%H:%M:%S",
                                      time.localtime(ev.get("ts", 0))),
                        ev.get("kind"), json.dumps(ev.get("data") or {},
                                                   sort_keys=True)))
    deltas = d.get("counter_deltas", {})
    if deltas:
        lines.append("  counter deltas since start:")
        for k in sorted(deltas):
            lines.append("    %-40s %+g" % (k, deltas[k]))
    logs = d.get("logs", [])
    if logs:
        lines.append("  log tail (%d):" % len(logs))
        for rec in logs[-max_rows:]:
            lines.append("    %s %s %s  %s"
                         % (time.strftime("%H:%M:%S",
                                          time.localtime(rec.get("ts", 0))),
                            rec.get("level", "?")[:1], rec.get("src", ""),
                            rec.get("msg", "")))
    spans_tail = d.get("spans", [])
    if spans_tail:
        lines.append("  span tail (last %d):" % min(len(spans_tail),
                                                    max_rows))
        for s in spans_tail[-max_rows:]:
            lines.append("    %-28s %-10s %10.3fms  %s"
                         % (s.get("name"), s.get("cat"),
                            s.get("dur_ms", 0.0),
                            json.dumps(s.get("args") or {},
                                       sort_keys=True)))
    return "\n".join(lines)
