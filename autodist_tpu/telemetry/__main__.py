import sys

from autodist_tpu.telemetry.cli import main

if __name__ == "__main__":
    sys.exit(main())
