"""Goodput & straggler attribution: where the wall time actually went.

``Runner.step_stats()`` reports *that* time was lost (total vs steady
median × dispatches); this module reports *where*: a
:class:`GoodputReport` decomposes the training thread's wall time into
attributed buckets by walking the recorded span tree —

==================  ====================================================
bucket              spans whose SELF time it aggregates
==================  ====================================================
``compute``         ``runner.dispatch`` / ``dstep.dispatch`` self time
                    (the jitted program, minus everything nested below)
``collective_wait`` ``runner.barrier`` (staleness pacing / lockstep
                    waits), ``coord.backoff`` (control-plane retries)
``ps_wire``         ``ps.pull``/``ps.push``/``ps.apply``/``ps.absorb``,
                    ``dstep.pull_ps``/``dstep.flush_ps``
``host_input``      ``runner.feed`` (host→device batch placement),
                    ``prefetch.place``
``readback``        ``runner.readback`` (device→host metrics)
``checkpoint``      every ``ckpt`` category span on the training thread
                    (async writer-thread time overlaps compute and is
                    deliberately NOT charged against the wall)
``rollback_replay`` ``sentinel.rollback`` self time (the restore's own
                    ckpt spans land in ``checkpoint``)
``other``           everything else (fit-loop bookkeeping, spans this
                    table does not know)
==================  ====================================================

**Self time** is a span's duration minus its same-thread children's, so
every nanosecond of the wall is attributed exactly once: the buckets sum
to the root spans' wall time *by construction* (the acceptance bound is
2% to absorb ring-buffer drops). Sampled traces cannot be decomposed —
the report flags itself ``approximate`` and the buckets scale by the
stride only in aggregate.

The cross-worker half (:func:`cluster_goodput`) runs the same
decomposition per process on a merged/scraped trace and adds **step-time
skew**: per-worker dispatch medians, the max/min skew ratio, and
straggler flags (median > ``flag_ratio`` × the cluster median).

The *online* straggler signal is :class:`StragglerEwma` — the Runner
feeds it per-dispatch wall times; sustained z-score outliers flip the
``telemetry.straggler`` gauge, emit instants, and (multi-process) mark
``straggler/<worker>`` on the coordination service so the chief's
watchdog can tell slow-but-alive from dead.
"""
import dataclasses
import math
import statistics
from typing import Dict, List, Optional

from autodist_tpu import const
from autodist_tpu.telemetry import spans as spans_lib

BUCKETS = ("compute", "collective_wait", "ps_wire", "host_input",
           "readback", "checkpoint", "rollback_replay", "other")

_SPAN_BUCKET = {
    "runner.dispatch": "compute", "dstep.dispatch": "compute",
    "runner.barrier": "collective_wait", "coord.backoff": "collective_wait",
    "ps.pull": "ps_wire", "ps.push": "ps_wire", "ps.apply": "ps_wire",
    "ps.absorb": "ps_wire", "dstep.pull_ps": "ps_wire",
    "dstep.flush_ps": "ps_wire",
    "ps_service.publish": "ps_wire", "ps_service.apply": "ps_wire",
    "runner.feed": "host_input", "prefetch.place": "host_input",
    "runner.readback": "readback",
    "sentinel.rollback": "rollback_replay",
}
_CAT_BUCKET = {"ckpt": "checkpoint"}

DISPATCH_SPAN = "runner.dispatch"


def classify(name: str, cat: str) -> str:
    """The bucket one span's SELF time belongs to."""
    bucket = _SPAN_BUCKET.get(name)
    if bucket is not None:
        return bucket
    return _CAT_BUCKET.get(cat, "other")


# --------------------------------------------------------------- reports


@dataclasses.dataclass
class GoodputReport:
    """One process's attributed wall-time decomposition (seconds)."""

    wall_s: float
    buckets: Dict[str, float]
    num_dispatches: int
    dispatch_median_s: Optional[float]
    dispatch_p90_s: Optional[float]
    first_dispatch_s: Optional[float]     # includes the XLA compile
    approximate: bool = False             # sampled trace or ring drops
    dropped_events: int = 0

    @property
    def attributed_s(self) -> float:
        return sum(self.buckets.values())

    @property
    def coverage(self) -> Optional[float]:
        """attributed / wall — 1.0 ± float noise by construction; < 1
        signals ring-buffer drops (see ``approximate``)."""
        return (self.attributed_s / self.wall_s) if self.wall_s > 0 else None

    @property
    def goodput(self) -> Optional[float]:
        """Fraction of the wall spent computing (the bucket the job
        exists for)."""
        if self.wall_s <= 0:
            return None
        return min(1.0, self.buckets.get("compute", 0.0) / self.wall_s)

    def to_dict(self) -> dict:
        return {
            "wall_s": round(self.wall_s, 6),
            "buckets": {k: round(v, 6) for k, v in self.buckets.items()},
            "attributed_s": round(self.attributed_s, 6),
            "coverage": (round(self.coverage, 4)
                         if self.coverage is not None else None),
            "goodput": (round(self.goodput, 4)
                        if self.goodput is not None else None),
            "num_dispatches": self.num_dispatches,
            "dispatch_median_s": (round(self.dispatch_median_s, 6)
                                  if self.dispatch_median_s is not None
                                  else None),
            "dispatch_p90_s": (round(self.dispatch_p90_s, 6)
                               if self.dispatch_p90_s is not None else None),
            "first_dispatch_s": (round(self.first_dispatch_s, 6)
                                 if self.first_dispatch_s is not None
                                 else None),
            "approximate": self.approximate,
            "dropped_events": self.dropped_events,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "GoodputReport":
        return cls(wall_s=float(d.get("wall_s", 0.0)),
                   buckets={k: float(v)
                            for k, v in d.get("buckets", {}).items()},
                   num_dispatches=int(d.get("num_dispatches", 0)),
                   dispatch_median_s=d.get("dispatch_median_s"),
                   dispatch_p90_s=d.get("dispatch_p90_s"),
                   first_dispatch_s=d.get("first_dispatch_s"),
                   approximate=bool(d.get("approximate", False)),
                   dropped_events=int(d.get("dropped_events", 0)))

    def save(self, path: str) -> str:
        import json
        import os
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        return path

    def format_table(self) -> str:
        lines = ["goodput report: wall=%.6gs dispatches=%d%s"
                 % (self.wall_s, self.num_dispatches,
                    " (APPROXIMATE: sampled/dropped spans)"
                    if self.approximate else "")]
        lines.append("  %-16s %12s %8s" % ("bucket", "seconds", "share"))
        for name in BUCKETS:
            sec = self.buckets.get(name, 0.0)
            share = sec / self.wall_s if self.wall_s > 0 else 0.0
            lines.append("  %-16s %12.6f %7.1f%%" % (name, sec,
                                                     100.0 * share))
        lines.append("  %-16s %12.6f %7.1f%%"
                     % ("(attributed)", self.attributed_s,
                        100.0 * (self.coverage or 0.0)))
        if self.dispatch_median_s is not None:
            lines.append("  dispatch: median=%.6gs p90=%.6gs first=%s"
                         % (self.dispatch_median_s, self.dispatch_p90_s,
                            "%.6gs" % self.first_dispatch_s
                            if self.first_dispatch_s is not None else "-"))
        return "\n".join(lines)


# ----------------------------------------------------------- event walks


def _normalize_recorder(rec) -> List[dict]:
    return [{"name": e.name, "cat": e.cat, "ts": e.ts_ns / 1e3,
             "dur": e.dur_ns / 1e3, "tid": e.tid, "pid": rec.pid,
             "id": e.span_id, "parent": e.parent_id}
            for e in rec.events()]


def _normalize_trace(trace: dict) -> List[dict]:
    out = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        out.append({"name": e.get("name", "?"), "cat": e.get("cat", ""),
                    "ts": float(e.get("ts", 0.0)),
                    "dur": float(e.get("dur", 0.0)),
                    "tid": e.get("tid", 0), "pid": e.get("pid", 0),
                    "id": args.get("span_id", 0),
                    "parent": args.get("parent_id", 0)})
    return out


def _training_tid(events: List[dict]) -> Optional[int]:
    """The thread whose wall time the decomposition attributes: the one
    holding ``runner.fit`` (or, failing that, the most dispatches, or
    the most recorded time)."""
    fits = [e for e in events if e["name"] == "runner.fit"]
    if fits:
        return max(fits, key=lambda e: e["dur"])["tid"]
    per_tid: Dict[int, int] = {}
    for e in events:
        if e["name"] == DISPATCH_SPAN:
            per_tid[e["tid"]] = per_tid.get(e["tid"], 0) + 1
    if per_tid:
        return max(per_tid, key=per_tid.get)
    per_tid_time: Dict[int, float] = {}
    for e in events:
        per_tid_time[e["tid"]] = per_tid_time.get(e["tid"], 0.0) + e["dur"]
    return (max(per_tid_time, key=per_tid_time.get)
            if per_tid_time else None)


def breakdown_from_events(events: List[dict],
                          tid: Optional[int] = None) -> GoodputReport:
    """Self-time decomposition of one process's events (µs in, s out).
    Only spans on the training thread participate — background threads
    (async checkpoint writer, PS apply loop, serving) overlap the wall
    rather than spending it."""
    if tid is None:
        tid = _training_tid(events)
    mine = [e for e in events if e["tid"] == tid and e["dur"] > 0]
    ids = {e["id"] for e in mine}
    child_time: Dict[int, float] = {}
    for e in mine:
        if e["parent"] in ids:
            child_time[e["parent"]] = (child_time.get(e["parent"], 0.0)
                                       + e["dur"])
    buckets = dict.fromkeys(BUCKETS, 0.0)
    wall_us = 0.0
    dispatch_durs: List[float] = []
    for e in mine:
        self_us = max(e["dur"] - child_time.get(e["id"], 0.0), 0.0)
        buckets[classify(e["name"], e["cat"])] += self_us / 1e6
        if e["parent"] not in ids:
            wall_us += e["dur"]
        if e["name"] == DISPATCH_SPAN:
            dispatch_durs.append(e["dur"] / 1e6)
    n = len(dispatch_durs)
    steady = sorted(dispatch_durs[1:]) if n > 1 else []
    return GoodputReport(
        wall_s=wall_us / 1e6,
        buckets=buckets,
        num_dispatches=n,
        dispatch_median_s=(statistics.median(steady) if steady
                           else (dispatch_durs[0] if n else None)),
        dispatch_p90_s=(steady[min(len(steady) - 1,
                                   math.floor(0.9 * len(steady)))]
                        if steady else None),
        first_dispatch_s=dispatch_durs[0] if n else None)


def build_report(recorder: Optional[spans_lib.TraceRecorder] = None
                 ) -> GoodputReport:
    """GoodputReport for one live recorder (``Runner.goodput_report``'s
    backend)."""
    rec = recorder if recorder is not None else spans_lib.get_recorder()
    report = breakdown_from_events(_normalize_recorder(rec))
    report.dropped_events = rec.dropped_events
    report.approximate = rec.sample > 1 or rec.dropped_events > 0
    return report


def report_from_trace(trace: dict) -> Dict[int, GoodputReport]:
    """Per-pid reports from an exported (possibly merged) trace file —
    the ``python -m autodist_tpu.telemetry goodput`` backend."""
    events = _normalize_trace(trace)
    pids = sorted({e["pid"] for e in events})
    return {pid: breakdown_from_events([e for e in events
                                        if e["pid"] == pid])
            for pid in pids}


# ------------------------------------------------------- cluster analysis


def cluster_goodput(trace: dict, flag_ratio: float = 1.5) -> dict:
    """Cross-worker skew + straggler attribution over a merged trace:
    per-pid goodput reports, per-pid dispatch medians, the max/min skew
    ratio, and the pids flagged as stragglers (median > ``flag_ratio``
    × the FASTEST worker's median — the fastest worker is the honest
    baseline of what the hardware can do; a cluster-median baseline
    cannot flag anything in a 2-worker cluster, and a half-degraded
    fleet drags the median toward the stragglers). Labels come from the
    trace's process_name metadata when present."""
    labels: Dict[int, str] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            labels[e.get("pid", 0)] = (e.get("args") or {}).get("name", "")
    reports = report_from_trace(trace)
    medians = {pid: r.dispatch_median_s for pid, r in reports.items()
               if r.dispatch_median_s}
    skew = (max(medians.values()) / min(medians.values())
            if len(medians) > 1 and min(medians.values()) > 0 else 1.0)
    baseline = min(medians.values()) if len(medians) > 1 else None
    stragglers = sorted(
        pid for pid, m in medians.items()
        if baseline and m > flag_ratio * baseline)
    return {
        "workers": {pid: dict(reports[pid].to_dict(),
                              label=labels.get(pid, str(pid)))
                    for pid in reports},
        "step_medians_s": {pid: round(m, 6)
                           for pid, m in medians.items()},
        "skew_ratio": round(skew, 4),
        "stragglers": [{"pid": pid, "label": labels.get(pid, str(pid)),
                        "median_s": round(medians[pid], 6)}
                       for pid in stragglers],
    }


# --------------------------------------------------------- online EWMA


class StragglerEwma:
    """Online per-dispatch straggler detector (the Runner feeds it one
    wall-time sample per dispatch). Sustained z-score outliers —
    ``ADT_STRAGGLER_Z`` sigma above the EWMA baseline for
    ``ADT_STRAGGLER_PATIENCE`` consecutive dispatches — flag this worker
    as *slow-but-alive*; recovery (one in-band sample) clears the flag.
    The EWMA ingests only non-flagged samples, so a long degradation
    cannot drag its own baseline up and hide."""

    def __init__(self, alpha: float = 0.1, zscore: Optional[float] = None,
                 patience: Optional[int] = None, warmup: int = 8):
        self.alpha = alpha
        self.zscore = (zscore if zscore is not None
                       else const.ENV.ADT_STRAGGLER_Z.val)
        self.patience = max(int(patience if patience is not None
                                else const.ENV.ADT_STRAGGLER_PATIENCE.val),
                            1)
        self.warmup = warmup
        self._mean: Optional[float] = None
        self._var = 0.0
        self._n = 0
        self._streak = 0
        self.flagged = False
        self.last_z: Optional[float] = None
        self.flags = 0

    def observe(self, dur_s: float) -> Optional[str]:
        """Ingest one dispatch wall time. Returns ``"flag"`` on the
        transition into the straggling state, ``"clear"`` on recovery,
        None otherwise (the caller emits telemetry on transitions)."""
        if self._mean is None:
            self._mean, self._n = dur_s, 1
            return None
        std = math.sqrt(max(self._var, 0.0))
        z = (dur_s - self._mean) / (std + 1e-9)
        self.last_z = z
        if self._n >= self.warmup and z > self.zscore:
            self._streak += 1
            if self._streak >= self.patience and not self.flagged:
                self.flagged = True
                self.flags += 1
                return "flag"
            return None  # an outlier must not inflate its own baseline
        self._streak = 0
        delta = dur_s - self._mean
        self._mean += self.alpha * delta
        self._var = ((1.0 - self.alpha)
                     * (self._var + self.alpha * delta * delta))
        self._n += 1
        if self.flagged:
            self.flagged = False
            return "clear"
        return None

    def stats(self) -> dict:
        return {"flagged": self.flagged, "flags": self.flags,
                "last_z": (round(self.last_z, 3)
                           if self.last_z is not None else None),
                "ewma_s": (round(self._mean, 6)
                           if self._mean is not None else None)}
