"""WithRemat — gradient rematerialization as a composable strategy wrapper.

A TPU-native graph-level knob the reference had no equivalent for (its
strategy space was purely about gradient synchronization): wraps ANY
strategy builder and sets ``graph_config.remat``, making the lowering
compute gradients through ``jax.checkpoint`` — the backward pass
recomputes forward activations instead of storing them, trading FLOPs for
HBM so larger batches/models fit. Policies:

- ``"full"``  — save nothing but inputs (maximum HBM saving, ~1/3 more
  FLOPs for a transformer);
- ``"dots"``  — save matmul outputs without batch dims
  (``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``): the
  usual sweet spot — elementwise/norm activations are recomputed, the
  expensive contractions are not.

The knob rides the serialized strategy like every other field, so workers
lower the identical rematerialized program.

    ad = adt.AutoDist(strategy_builder=WithRemat(strategy.AllReduce(),
                                                 policy="dots"))
"""
from autodist_tpu.strategy.base import Strategy, StrategyBuilder

REMAT_POLICIES = ("full", "dots")


def remat_transform(policy: str):
    """Policy name -> function wrapper. The single source for the policy
    set — WithRemat validates against it and the lowering applies it, so
    the two can never drift."""
    import jax
    if policy == "full":
        return jax.checkpoint
    if policy == "dots":
        return lambda f: jax.checkpoint(
            f, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    raise ValueError("unknown remat policy %r (have %s)"
                     % (policy, list(REMAT_POLICIES)))


class WithRemat(StrategyBuilder):
    def __init__(self, inner: StrategyBuilder, policy: str = "full"):
        if policy not in REMAT_POLICIES:
            raise ValueError("unknown remat policy %r (have %s)"
                             % (policy, list(REMAT_POLICIES)))
        self._inner = inner
        self._policy = policy

    def build(self, model_item, resource_spec) -> Strategy:
        strategy = self._inner.build(model_item, resource_spec)
        strategy.graph_config.remat = self._policy
        return strategy
