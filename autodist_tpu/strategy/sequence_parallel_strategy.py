"""Sequence-parallel strategy builder (beyond the reference).

Extends the AllReduce data-parallel strategy with a second mesh axis over
which the *sequence* dimension of the batch is sharded — the strategy axis
the reference's proto anticipated but never grew
(reference ``proto/strategy.proto:36-41``; SURVEY §5 long-context note).

The model must be SP-aware: attention via ``ops.attention.make_attn_fn``
(ring or Ulysses) and positions/losses via ``parallel/sequence.py`` helpers.
``models/lm.py`` / ``models/bert.py`` support this through their
``attn_fn`` / position-ids plumbing.
"""
from autodist_tpu import const
from autodist_tpu.strategy.all_reduce_strategy import AllReduce
from autodist_tpu.strategy.base import Strategy


class SequenceParallelAR(AllReduce):
    def __init__(self, seq_shards: int, attention: str = "ring",
                 chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor", seq_keys=None):
        super().__init__(chunk_size, all_reduce_spec, compressor)
        if seq_shards < 1:
            raise ValueError("seq_shards must be >= 1")
        self.seq_shards = seq_shards
        self.attention = attention  # metadata: which attn the model should use
        # batch-leaf names whose dim 1 is the sequence dim; None = every
        # rank>=2 leaf (set this when the batch mixes token arrays with
        # other rank>=2 leaves, e.g. one-hot labels)
        self.seq_keys = list(seq_keys) if seq_keys else None

    def build(self, model_item, resource_spec) -> Strategy:
        strategy = super().build(model_item, resource_spec)
        n_devices = len(strategy.graph_config.replicas)
        if n_devices % self.seq_shards != 0:
            raise ValueError("%d devices not divisible by seq_shards=%d"
                             % (n_devices, self.seq_shards))
        strategy.graph_config.mesh_shape = {
            const.DATA_AXIS: n_devices // self.seq_shards,
            const.SEQUENCE_AXIS: self.seq_shards,
        }
        strategy.graph_config.seq_axis = const.SEQUENCE_AXIS
        strategy.graph_config.seq_feed_keys = self.seq_keys
        return strategy
