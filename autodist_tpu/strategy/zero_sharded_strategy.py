"""ZeroSharded strategy: replicated params, cross-replica sharded update.

The ZeRO stage-1 weight update (arXiv 2004.13336) as a zoo builder: every
eligible dense variable gets a :class:`ZeroShardedSynchronizer` — the
lowering reduce-scatters its gradient over the data axis, applies the
optimizer to each replica's owned 1/P flat shard only (optimizer state is
created sharded, never materialized whole), and all-gathers the update
back onto the replicated params. Same wire bytes as AllReduce
(rs + ag = the 2(P-1)/P ring factor), ~(P-1)/P less optimizer-state HBM
per chip — the memory/speed trade axis the PR 4 static HBM analyzer
(ADT501) gates on and the PR 6 searcher exploits.

Ineligible variables fall back to plain AllReduce, so built plans lint
clean by construction (the searcher's canon applies the same gates):

- sparse (gather-indexed) variables: the reduce-scatter would densify
  their batch-row-sized gradient to the full table (ADT312); they keep
  the (ids, values) sparse wire.
- variables smaller than one per-replica shard: the padding + collective
  launch overhead exceeds the opt-state saving (ADT313).

``wire_dtype="int8"`` additionally quantizes both wire crossings through
the blockwise codec (dense float vars of >= one scale block; the rest
stay fp32 — ADT310/311 by construction, same as the AllReduce builder).
"""
from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                        Strategy, StrategyBuilder, VarConfig,
                                        ZeroShardedSynchronizer)
from autodist_tpu.strategy.ps_strategy import replica_devices


def zero_shardable(info, num_replicas: int) -> bool:
    """The ONE eligibility gate for ZeroSharded sync, shared by this
    builder, the search space's canon, and the ADT313 lint (ADT312/313
    stay un-emitted by construction): dense variables with at least one
    element per replica shard."""
    if info is None or getattr(info, "sparse", False):
        return False
    if getattr(info, "num_elements", 0) < max(int(num_replicas), 1):
        return False
    return True


def zero_wire_quantizable(info, num_replicas: int) -> bool:
    """int8 eligibility for the ZeRO rs/ag wire: dense float AND at
    least one scale block PER SHARD — the kernel rounds each replica's
    shard up to whole blocks, so a variable below ``P x block`` elements
    would ship MORE padded int8 bytes than the fp32 wire. Shared by the
    builder and the searcher's canon so the cost model's padded pricing
    and the emitted plans agree."""
    from autodist_tpu.parallel.collectives import (wire_block_size,
                                                   wire_quantizable)
    if not wire_quantizable(info):
        return False
    return (getattr(info, "num_elements", 0)
            >= max(int(num_replicas), 1) * wire_block_size())


class ZeroSharded(StrategyBuilder):
    def __init__(self, chunk_size: int = 128, wire_dtype: str = "fp32",
                 compute_dtype: str = "f32", overlap: bool = False):
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        # overlap: barrier-chained per-unit sync schedule (reverse layer
        # order) — the per-var reduce-scatters launch as their gradients
        # become ready instead of in one epilogue
        self.overlap = overlap
        # chunk_size buckets the AllReduce FALLBACK vars (small/sparse)
        self.chunk_size = chunk_size
        # "int8": blockwise-quantized rs + update all-gather wire (dense
        # float vars of >= one scale block only — ADT310/311)
        self.wire_dtype = wire_dtype
        # "bf16": managed bf16 compute beside the f32 sharded master —
        # the 2004.13336 pairing (bf16 compute, f32 shard update)
        self.compute_dtype = compute_dtype

    def build(self, model_item, resource_spec) -> Strategy:
        n_replicas = max(len(resource_spec.devices), 1)
        nodes = []
        for idx, name in enumerate(model_item.trainable_var_names):
            info = model_item.var_infos.get(name)
            if zero_shardable(info, n_replicas):
                quantizable = zero_wire_quantizable(info, n_replicas)
                nodes.append(VarConfig(
                    var_name=name,
                    synchronizer=ZeroShardedSynchronizer(
                        wire_dtype=(self.wire_dtype if quantizable
                                    else "fp32"))))
            else:
                nodes.append(VarConfig(
                    var_name=name,
                    synchronizer=AllReduceSynchronizer(
                        group=idx // self.chunk_size)))
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(
                            replicas=replica_devices(resource_spec),
                            compute_dtype=self.compute_dtype,
                            overlap=self.overlap))
