"""PS with greedy load balancing by variable byte size.

Analog of reference ``autodist/strategy/ps_lb_strategy.py:63-117``
(``byte_size_load_fn`` at ``:88-117``): variables are assigned to parameter
servers greedily, largest-first onto the least-loaded server. This is the
reference's *default* builder (``autodist/autodist.py:70``) and ours too.
"""
import heapq

from autodist_tpu.strategy.base import (GraphConfig, PSSynchronizer, Strategy,
                                        StrategyBuilder, VarConfig)
from autodist_tpu.strategy.ps_strategy import reduction_devices, replica_devices


def byte_size_load_fn(var_info) -> float:
    """Estimated PS load for one variable, in bytes (analog of reference
    ``ps_lb_strategy.py:88-117``). Sparse (embedding) variables are accessed
    row-wise, so their effective load is discounted by the row count."""
    size = float(var_info.byte_size)
    if var_info.sparse and var_info.shape:
        # only a minibatch worth of rows moves per step; approximate with
        # one row's bytes times a nominal 128-row batch, capped by full size
        rows = max(int(var_info.shape[0]), 1)
        size = min(size, size / rows * 128.0)
    return max(size, 1.0)


def greedy_assign(var_infos, destinations, load_fn=byte_size_load_fn):
    """Greedy bin packing: sort by load desc, place on least-loaded PS."""
    heap = [(0.0, i, dest) for i, dest in enumerate(destinations)]
    heapq.heapify(heap)
    assignment = {}
    for info in sorted(var_infos, key=lambda v: -load_fn(v)):
        load, i, dest = heapq.heappop(heap)
        assignment[info.name] = dest
        heapq.heappush(heap, (load + load_fn(info), i, dest))
    return assignment


class PSLoadBalancing(StrategyBuilder):
    def __init__(self, local_proxy_variable: bool = False, sync: bool = True,
                 staleness: int = 0):
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        if staleness > 0:
            assert sync, "staleness is only meaningful for sync training"

    def build(self, model_item, resource_spec) -> Strategy:
        destinations = reduction_devices(resource_spec)
        infos = [model_item.var_infos[n] for n in model_item.trainable_var_names]
        assignment = greedy_assign(infos, destinations)
        nodes = [
            VarConfig(
                var_name=info.name,
                synchronizer=PSSynchronizer(
                    reduction_destination=assignment[info.name],
                    local_replication=self._local_proxy_variable,
                    sync=self._sync,
                    staleness=self._staleness))
            for info in infos
        ]
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(replicas=replica_devices(resource_spec)))
