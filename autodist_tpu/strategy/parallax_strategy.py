"""Parallax hybrid strategy: dense -> AllReduce, sparse -> load-balanced PS.

Analog of reference ``autodist/strategy/parallax_strategy.py:24-71``
(after Parallax, arXiv 1808.02621): dense-gradient variables synchronize via
all-reduce (bandwidth-optimal on ICI) while sparse/embedding variables go to
load-balanced parameter servers (row-indexed traffic is cheaper through a
sharded-parameter path than dense all-reduce of a huge mostly-zero grad).
Sparseness comes from ``ModelItem``'s gather-detection — the analog of the
reference's IndexedSlices check.
"""
from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                        PSSynchronizer, Strategy, StrategyBuilder,
                                        VarConfig)
from autodist_tpu.strategy.ps_lb_strategy import byte_size_load_fn, greedy_assign
from autodist_tpu.strategy.ps_strategy import reduction_devices, replica_devices


class Parallax(StrategyBuilder):
    def __init__(self, chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor",
                 local_proxy_variable: bool = False, sync: bool = True,
                 staleness: int = 0, require_sparse: bool = False):
        self.chunk_size = chunk_size
        self.all_reduce_spec = all_reduce_spec
        self.compressor = compressor
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        # the whole point of Parallax is the dense/sparse split — a user
        # who picked it for an embedding model can demand that the sparse
        # wire actually engages (lowering raises on silent dense fallback)
        self._require_sparse = require_sparse

    def build(self, model_item, resource_spec) -> Strategy:
        infos = [model_item.var_infos[n] for n in model_item.trainable_var_names]
        dense = [i for i in infos if not i.sparse]
        sparse = [i for i in infos if i.sparse]
        destinations = reduction_devices(resource_spec)
        sparse_assignment = greedy_assign(sparse, destinations, byte_size_load_fn)
        nodes = []
        for idx, info in enumerate(dense):
            nodes.append(VarConfig(
                var_name=info.name,
                synchronizer=AllReduceSynchronizer(
                    spec=self.all_reduce_spec, compressor=self.compressor,
                    group=idx // self.chunk_size)))
        for info in sparse:
            nodes.append(VarConfig(
                var_name=info.name,
                synchronizer=PSSynchronizer(
                    reduction_destination=sparse_assignment[info.name],
                    local_replication=self._local_proxy_variable,
                    sync=self._sync, staleness=self._staleness)))
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(
                            replicas=replica_devices(resource_spec),
                            require_sparse=self._require_sparse))
