"""Strategy builders (reference ``autodist/strategy/``)."""
from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                        PSSynchronizer, Strategy, StrategyBuilder,
                                        StrategyCompiler, VarConfig,
                                        ZeroShardedSynchronizer)
from autodist_tpu.strategy.ps_strategy import PS
from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing
from autodist_tpu.strategy.partitioned_ps_strategy import PartitionedPS
from autodist_tpu.strategy.uneven_partition_ps_strategy import UnevenPartitionedPS
from autodist_tpu.strategy.all_reduce_strategy import AllReduce
from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR
from autodist_tpu.strategy.random_axis_partition_all_reduce_strategy import (
    RandomAxisPartitionAR)
from autodist_tpu.strategy.parallax_strategy import Parallax
from autodist_tpu.strategy.sequence_parallel_strategy import SequenceParallelAR
from autodist_tpu.strategy.tensor_parallel_strategy import TensorParallel
from autodist_tpu.strategy.pipeline_parallel_strategy import PipelineParallel
from autodist_tpu.strategy.expert_parallel_strategy import ExpertParallel
from autodist_tpu.strategy.zero_sharded_strategy import ZeroSharded
from autodist_tpu.strategy.auto_strategy import AutoStrategy
from autodist_tpu.strategy.remat import WithRemat

__all__ = ["Strategy", "StrategyBuilder", "StrategyCompiler", "VarConfig",
           "GraphConfig", "PSSynchronizer", "AllReduceSynchronizer",
           "ZeroShardedSynchronizer",
           "PS", "PSLoadBalancing", "PartitionedPS", "UnevenPartitionedPS",
           "AllReduce", "PartitionedAR", "RandomAxisPartitionAR", "Parallax",
           "SequenceParallelAR", "TensorParallel", "PipelineParallel",
           "ExpertParallel", "ZeroSharded", "AutoStrategy", "WithRemat"]
