"""Expert-parallel (MoE) strategy builder (beyond the reference).

Adds the ``expert`` mesh axis: expert-stacked variables matching the
model's rules shard their stack dim over it and tokens route with
all_to_all (``parallel/expert.py``). The batch dim shards over
data x expert jointly (``GraphConfig.batch_axes``) so every device holds
distinct tokens — the expert axis doubles as extra data parallelism for the
dense layers, the standard MoE-EP arrangement (GShard, arXiv 2006.16668).
"""
from autodist_tpu import const
from autodist_tpu.strategy.all_reduce_strategy import AllReduce
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.strategy.tensor_parallel_strategy import (
    MpRules, add_frozen_nodes, apply_mp_rules)
from autodist_tpu.utils import logging


class ExpertParallel(AllReduce):
    """(data x expert) mesh with all_to_all token routing."""

    def __init__(self, ep_shards: int, mp_rules: MpRules,
                 chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor"):
        super().__init__(chunk_size, all_reduce_spec, compressor)
        if ep_shards < 1:
            raise ValueError("ep_shards must be >= 1")
        self.ep_shards = ep_shards
        self.mp_rules = list(mp_rules)

    def build(self, model_item, resource_spec) -> Strategy:
        strategy = super().build(model_item, resource_spec)
        n_devices = len(strategy.graph_config.replicas)
        if n_devices % self.ep_shards != 0:
            raise ValueError("%d devices not divisible by ep_shards=%d"
                             % (n_devices, self.ep_shards))
        mesh_shape = {const.DATA_AXIS: n_devices // self.ep_shards,
                      const.EXPERT_AXIS: self.ep_shards}
        strategy.graph_config.mesh_shape = mesh_shape
        strategy.graph_config.batch_axes = [const.DATA_AXIS, const.EXPERT_AXIS]
        add_frozen_nodes(strategy, model_item)
        n = apply_mp_rules(strategy, self.mp_rules)
        logging.info("ExpertParallel: %d/%d vars expert-sharded, mesh %s",
                     n, len(strategy.node_config), mesh_shape)
        return strategy
