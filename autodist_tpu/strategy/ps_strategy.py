"""PS strategy: every variable synchronized through a single parameter server.

Analog of reference ``autodist/strategy/ps_strategy.py:38-55``: all vars get a
``PSSynchronizer`` whose reduction destination is the first node's host CPU;
replicas are all compute devices (TPU chips; on chip-less nodes, CPUs —
mirroring "CPU-only nodes contribute CPUs").
"""
from autodist_tpu.strategy.base import (GraphConfig, PSSynchronizer, Strategy,
                                        StrategyBuilder, VarConfig)


def reduction_devices(resource_spec):
    """One host-CPU reduction device per node (PS candidates)."""
    return ["%s:CPU:0" % addr for addr in resource_spec.node_addresses]


def replica_devices(resource_spec):
    return [d.name_string() for d in resource_spec.devices]


class PS(StrategyBuilder):
    def __init__(self, local_proxy_variable: bool = False, sync: bool = True,
                 staleness: int = 0, require_sparse: bool = False,
                 wire_dtype: str = "fp32", compute_dtype: str = "f32"):
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        self._require_sparse = require_sparse
        # "int8": host<->device PS wire ships blockwise int8 + scales
        # (no-proxy dense float vars only; others keep fp32 — ADT310)
        self._wire_dtype = wire_dtype
        # "bf16": managed bf16 compute tier (f32 master stays on the PS)
        self._compute_dtype = compute_dtype
        if staleness > 0:
            assert sync, "staleness is only meaningful for sync training"

    def build(self, model_item, resource_spec) -> Strategy:
        from autodist_tpu.parallel.collectives import wire_quantizable
        destination = reduction_devices(resource_spec)[0]

        def wire_for(name):
            # dense float, no proxy, >= one scale block (ADT310/311 stay
            # un-emitted by construction — the searcher's canon gate)
            info = model_item.var_infos.get(name)
            if self._local_proxy_variable or not wire_quantizable(
                    info, min_block=True):
                return "fp32"
            return self._wire_dtype

        nodes = [
            VarConfig(
                var_name=name,
                synchronizer=PSSynchronizer(
                    reduction_destination=destination,
                    local_replication=self._local_proxy_variable,
                    sync=self._sync,
                    staleness=self._staleness,
                    wire_dtype=wire_for(name)))
            for name in model_item.trainable_var_names
        ]
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(
                            replicas=replica_devices(resource_spec),
                            require_sparse=self._require_sparse,
                            compute_dtype=self._compute_dtype))
