"""Strategy intermediate representation + builder/compiler base classes.

Analog of reference ``autodist/strategy/base.py`` and the protobuf schemas
``proto/strategy.proto:31-69`` / ``proto/synchronizers.proto``. The Strategy
is the contract between the frontend (builders, pure functions of
(ModelItem, ResourceSpec)) and the backend lowering
(``autodist_tpu/kernel/graph_transformer.py``): per-variable it says how to
synchronize gradients (PS or AllReduce, with partitioning, staleness,
compression, grouping), and per-graph which devices carry data-parallel
replicas.

Serialization is JSON on disk under ``/tmp/autodist_tpu/strategies/<id>``
(the reference serializes protobuf under ``/tmp/autodist/strategies``,
reference ``strategy/base.py:78-99``) so the chief can write a strategy and
every worker can load the identical bytes — all processes then lower the same
plan independently, exactly the reference's
"every node transforms its own graph" architecture
(reference ``docs/design/architecture.rst:43-47``).
"""
import dataclasses
import datetime
import json
import os
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Union

from autodist_tpu import const
from autodist_tpu.analysis import partition as partition_lib
from autodist_tpu.analysis.diagnostics import DiagnosticError, error
from autodist_tpu.utils import logging


# ------------------------------------------------------------- synchronizers


@dataclasses.dataclass
class PSSynchronizer:
    """Parameter-server sync config (reference ``synchronizers.proto:26-31``).

    On TPU, ``reduction_destination`` names the device that *owns* the
    variable's update computation; gradients are reduced to the owner and the
    updated value is re-broadcast (or cached via proxy, see
    ``parallel/ps.py``).

    ``wire_dtype`` ("fp32" | "int8") sets the host<->device wire format of
    the no-proxy (host-resident) PS path: "int8" ships values and pushed
    gradients as blockwise-scaled int8 + f32 scales
    (``parallel/collectives.py`` codec) with dequantization at the store
    boundary — dense float variables only (the linter's ADT310)."""
    reduction_destination: str = ""
    local_replication: bool = False
    sync: bool = True
    staleness: int = 0
    wire_dtype: str = "fp32"

    kind = "PS"

    def to_dict(self):
        return {"kind": self.kind, "reduction_destination": self.reduction_destination,
                "local_replication": self.local_replication, "sync": self.sync,
                "staleness": self.staleness, "wire_dtype": self.wire_dtype}


@dataclasses.dataclass
class AllReduceSynchronizer:
    """All-reduce sync config (reference ``synchronizers.proto:37-57``).

    ``spec`` is the communication hint: AUTO lets XLA choose; ICI pins the
    reduce to the intra-slice interconnect; DCN to the cross-slice network
    (the reference's AUTO/NCCL/RING map onto AUTO/ICI/ICI).
    ``compressor`` names a class in ``parallel/compression.py``. ``group``
    buckets small all-reduces together (the reference feeds this to the
    ScopedAllocator grappler pass, ``all_reduce_strategy.py:60-67``; we feed
    it to our own gradient bucketing in ``parallel/collectives.py``).

    ``wire_dtype`` ("fp32" | "int8") sets the collective's wire format:
    "int8" lowers the gradient all-reduce to the blockwise-scaled
    two-phase quantized shape (quantize -> reduce-scatter int8 -> local
    dequant-accumulate -> quantize -> all-gather; EQuARX, arXiv
    2506.17615) with error feedback. Dense float unpartitioned wires only,
    and mutually exclusive with ``compressor`` (the linter's ADT310).

    ``schedule`` picks the collective algorithm the reduce lowers to:
    "auto" resolves per topology (hierarchical when the replica set
    spans a declared multi-host topology's slow level, ring otherwise);
    "ring" pins the flat single-ring all-reduce; "rhd" the recursive
    halving/doubling shape (reduce-scatter + all-gather, fewer latency
    hops for small payloads); "hier" the two-level intra-host
    reduce-scatter / leader all-reduce / intra-host all-gather
    composition (arXiv 2110.10548). An explicit "hier" on a flat mesh is
    refused back to ring by the resolver; a pinned "ring" spanning hosts
    is the analyzer's ADT520."""
    spec: str = "AUTO"        # AUTO | ICI | DCN (NCCL/RING accepted as aliases)
    compressor: str = "NoneCompressor"
    group: int = 0
    wire_dtype: str = "fp32"
    schedule: str = "auto"    # auto | ring | rhd | hier

    kind = "AllReduce"

    _SPEC_ALIASES = {"NCCL": "ICI", "RING": "ICI"}

    def __post_init__(self):
        self.spec = self._SPEC_ALIASES.get(self.spec, self.spec)
        self.schedule = (self.schedule or "auto").lower()

    def to_dict(self):
        return {"kind": self.kind, "spec": self.spec,
                "compressor": self.compressor, "group": self.group,
                "wire_dtype": self.wire_dtype, "schedule": self.schedule}


@dataclasses.dataclass
class ZeroShardedSynchronizer:
    """ZeRO-style sharded weight update (arXiv 2004.13336, stage 1).

    Params stay stored FULL (replicated) — the forward pass never pays a
    gather — but the gradient is reduce-scattered across the data axis,
    each replica applies the optimizer update to its owned 1/P flat shard
    only (optimizer state is *created* sharded, never materialized
    whole), and the updated shard's delta is all-gathered back onto the
    replicated params. Wire bytes equal an all-reduce (rs + ag = the same
    2(P-1)/P ring factor); per-chip optimizer-state footprint drops by
    ~(P-1)/P.

    ``wire_dtype`` ("fp32" | "int8") quantizes both wire crossings
    through the blockwise codec (``parallel/collectives.py``): the
    reduce-scatter payload ships int8 + f32 scales (local accumulation
    stays f32) and the all-gathered UPDATE ships the same way — the
    delta, not the params, so replicated param copies accumulate in full
    precision and stay bit-identical across replicas. Dense float
    variables of at least one scale block only (the linter's
    ADT310/311); sparse / model-parallel / partitioned variables cannot
    zero-shard at all (ADT312)."""
    wire_dtype: str = "fp32"

    kind = "ZeroSharded"

    def to_dict(self):
        return {"kind": self.kind, "wire_dtype": self.wire_dtype}


Synchronizer = Union[PSSynchronizer, AllReduceSynchronizer,
                     ZeroShardedSynchronizer]


SYNCHRONIZER_KINDS = ("PS", "AllReduce", "ZeroSharded")


def synchronizer_from_dict(d: dict, var_name: str = "") -> Synchronizer:
    """Deserialize one synchronizer config.

    ``var_name`` names the owning strategy node in every failure message
    (a serialized plan has hundreds of nodes — "unknown kind" without the
    variable is unactionable). Raises :class:`DiagnosticError`
    (``ADT301``, a ``ValueError``) on an unknown kind or invalid fields.
    """
    d = dict(d)
    kind = d.pop("kind", None)
    ctor = {"PS": PSSynchronizer, "AllReduce": AllReduceSynchronizer,
            "ZeroSharded": ZeroShardedSynchronizer}.get(kind)
    if ctor is None:
        raise DiagnosticError(error(
            "ADT301",
            "unknown synchronizer kind %r (allowed kinds: %s)"
            % (kind, ", ".join(SYNCHRONIZER_KINDS)), var=var_name,
            fixit="serialize synchronizers through PSSynchronizer/"
                  "AllReduceSynchronizer/ZeroShardedSynchronizer"
                  ".to_dict()"))
    try:
        return ctor(**d)
    except TypeError as e:
        raise DiagnosticError(error(
            "ADT301",
            "invalid %s synchronizer fields %s (%s)"
            % (kind, sorted(d), e), var=var_name))


# ------------------------------------------------------------------- nodes


@dataclasses.dataclass
class VarConfig:
    """Per-variable strategy node (reference ``strategy.proto:36-49`` Node).

    ``partitioner`` is a comma-joined per-axis shard-count string like
    ``"4,1"`` (reference ``kernel/partitioner.py:38-150`` PartitionerConfig);
    when set, ``part_configs`` holds one VarConfig per shard. ``shard_sizes``
    supports uneven partitioning (sizes along the split axis).

    ``mp_axes`` (TPU-native extension beyond the reference, which is
    data-parallel only — reference ``docs/design/architecture.rst:46-48``)
    maps tensor dim -> mesh axis name for *model-parallel* storage: the
    variable is stored sharded over that mesh axis and the compute consumes
    the LOCAL shard directly (tensor/pipeline/expert parallelism), unlike
    ``partitioner`` sharding which all-gathers the full value for compute
    (ZeRO-style storage sharding)."""
    var_name: str
    synchronizer: Optional[Synchronizer] = None
    partitioner: Optional[str] = None
    part_configs: List["VarConfig"] = dataclasses.field(default_factory=list)
    shard_sizes: Optional[List[int]] = None
    mp_axes: Optional[Dict[int, str]] = None

    @property
    def partition_axis(self) -> Optional[int]:
        """First split axis; raises ``DiagnosticError`` (ADT201, a clean
        ``ValueError``) on a malformed partitioner like ``"4,"`` or
        ``"a,1"`` — the same diagnostic the linter reports."""
        if not self.partitioner:
            return None
        return partition_lib.partition_axis_of(
            partition_lib.parse_partitioner(self.partitioner, self.var_name))

    @property
    def num_shards(self) -> int:
        if not self.partitioner:
            return 1
        return partition_lib.num_shards_of(
            partition_lib.parse_partitioner(self.partitioner, self.var_name))

    def to_dict(self):
        return {
            "var_name": self.var_name,
            "synchronizer": self.synchronizer.to_dict() if self.synchronizer else None,
            "partitioner": self.partitioner,
            "part_configs": [p.to_dict() for p in self.part_configs],
            "shard_sizes": self.shard_sizes,
            "mp_axes": ({str(k): v for k, v in self.mp_axes.items()}
                        if self.mp_axes else None),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "VarConfig":
        return cls(
            var_name=d["var_name"],
            synchronizer=(synchronizer_from_dict(d["synchronizer"],
                                                 var_name=d["var_name"])
                          if d.get("synchronizer") else None),
            partitioner=d.get("partitioner"),
            part_configs=[cls.from_dict(p) for p in d.get("part_configs", [])],
            shard_sizes=d.get("shard_sizes"),
            mp_axes=({int(k): v for k, v in d["mp_axes"].items()}
                     if d.get("mp_axes") else None),
        )


@dataclasses.dataclass
class GraphConfig:
    """Graph-level config (reference ``strategy.proto:60-69``): the replica
    devices (data-parallel axis) plus TPU-native mesh extensions the
    reference anticipated but never grew (``strategy.proto:36-41``)."""
    replicas: List[str] = dataclasses.field(default_factory=list)
    # extension axes beyond the reference (tensor/pipeline/sequence/expert)
    mesh_shape: Optional[Dict[str, int]] = None
    # when set, batch leaves of rank >= 2 shard their dim 1 (the sequence
    # dim) over this mesh axis — set by sequence-parallel builders
    seq_axis: Optional[str] = None
    # mesh axes the batch dim (dim 0) shards over; None -> just the data
    # axis. Expert-parallel strategies set ['data', 'expert'] so every
    # device sees distinct tokens
    batch_axes: Optional[List[str]] = None
    # with seq_axis set: the batch-leaf names whose dim 1 really is the
    # sequence dim. None = every rank>=2 leaf (legacy behavior — fine
    # when the batch is all token arrays, silently WRONG for e.g. one-hot
    # label leaves whose dim 1 is classes; set this to the token keys)
    seq_feed_keys: Optional[List[str]] = None
    # gradient rematerialization: None (store all activations), "full"
    # (jax.checkpoint — recompute the forward in the backward, minimum
    # HBM), or "dots" (save matmul outputs only). A graph-level transform
    # the TF reference had no equivalent for; on TPU it is the standard
    # HBM-for-FLOPs trade that lets bigger batches/models fit
    remat: Optional[str] = None
    # GPipe microbatch count for pipeline strategies — recorded so the
    # cost model can price the pipeline bubble ((S-1+M)/M compute
    # inflation) from the serialized strategy alone
    pp_microbatches: Optional[int] = None
    # pipeline schedule: "gpipe" (all-M activation residency), "1f1b"
    # (residency bounded at S in-flight microbatches; the model must build
    # its loss through pipeline_loss_1f1b), or "interleaved" (V virtual
    # stage chunks per rank, bubble cut to (S-1)/(V*M) — model builds
    # through pipeline_apply_interleaved) — priced by the cost model
    pp_schedule: Optional[str] = None
    # virtual-stage chunks per rank for the interleaved schedule (V >= 2)
    pp_virtual: Optional[int] = None
    # strict sparse wire: a builder that PLANNED on (ids, values) gradient
    # shipping (DLRM/NCF embedding strategies) sets this so a silent
    # fallback to dense sync — a >10x wire regression — raises in the
    # lowering instead of logging a warning. ADT_IS_TESTING implies it.
    require_sparse: bool = False
    # compute tier: "f32" (default) or "bf16" — with "bf16" the lowering
    # casts params and float batch leaves to bfloat16 for the forward/
    # backward, while master params, optimizer state, gradient
    # accumulation (every psum/reduce-scatter) and the loss/sentinel
    # verdict stay f32 — the f32-master discipline the ADT60x numerics
    # rules certify (analysis/numerics.py, rules.verify_numerics)
    compute_dtype: str = "f32"
    # communication–computation overlap: lower gradient sync as an ordered
    # schedule of per-unit collectives chained through optimization_barrier
    # (reverse layer order) instead of one epilogue, so XLA's latency-
    # hiding scheduler can run each collective under the remaining
    # backward compute. Values are bit-identical to the epilogue lowering
    # (the barrier is an identity op); ignored at 1 replica.
    overlap: bool = False

    def to_dict(self):
        return {"replicas": list(self.replicas), "mesh_shape": self.mesh_shape,
                "seq_axis": self.seq_axis, "batch_axes": self.batch_axes,
                "seq_feed_keys": self.seq_feed_keys,
                "remat": self.remat, "pp_microbatches": self.pp_microbatches,
                "pp_schedule": self.pp_schedule,
                "pp_virtual": self.pp_virtual,
                "require_sparse": self.require_sparse,
                "compute_dtype": self.compute_dtype,
                "overlap": self.overlap}

    @classmethod
    def from_dict(cls, d):
        return cls(replicas=list(d.get("replicas", [])),
                   mesh_shape=d.get("mesh_shape"),
                   seq_axis=d.get("seq_axis"),
                   batch_axes=d.get("batch_axes"),
                   seq_feed_keys=d.get("seq_feed_keys"),
                   remat=d.get("remat"),
                   pp_microbatches=d.get("pp_microbatches"),
                   pp_schedule=d.get("pp_schedule"),
                   pp_virtual=d.get("pp_virtual"),
                   require_sparse=bool(d.get("require_sparse", False)),
                   compute_dtype=d.get("compute_dtype", "f32") or "f32",
                   overlap=bool(d.get("overlap", False)))


# ----------------------------------------------------------------- strategy


class Strategy:
    """The per-variable distribution plan (reference ``strategy/base.py:28-99``)."""

    def __init__(self, node_config: Optional[List[VarConfig]] = None,
                 graph_config: Optional[GraphConfig] = None,
                 strategy_id: Optional[str] = None):
        self.id = strategy_id or datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y%m%dT%H%M%S%f")
        self.node_config: List[VarConfig] = node_config or []
        self.graph_config: GraphConfig = graph_config or GraphConfig()

    def to_dict(self) -> dict:
        return {"id": self.id,
                "node_config": [n.to_dict() for n in self.node_config],
                "graph_config": self.graph_config.to_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "Strategy":
        return cls(node_config=[VarConfig.from_dict(n) for n in d.get("node_config", [])],
                   graph_config=GraphConfig.from_dict(d.get("graph_config", {})),
                   strategy_id=d.get("id"))

    def serialize(self, path: Optional[str] = None) -> str:
        if path is None:
            os.makedirs(const.DEFAULT_SERIALIZATION_DIR, exist_ok=True)
            path = os.path.join(const.DEFAULT_SERIALIZATION_DIR, self.id)
        # write-then-rename: workers poll for this file and must never
        # observe a half-written strategy
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, sort_keys=True, indent=1)
        os.replace(tmp, path)
        return path

    @classmethod
    def deserialize(cls, strategy_id: Optional[str] = None, path: Optional[str] = None) -> "Strategy":
        if path is None:
            path = os.path.join(const.DEFAULT_SERIALIZATION_DIR, strategy_id)
        with open(path, "r") as f:
            return cls.from_dict(json.load(f))

    def find(self, var_name: str) -> Optional[VarConfig]:
        for n in self.node_config:
            if n.var_name == var_name:
                return n
        return None

    def __repr__(self):
        return "Strategy(id=%s, vars=%d, replicas=%d)" % (
            self.id, len(self.node_config), len(self.graph_config.replicas))

    def __str__(self):
        return json.dumps(self.to_dict(), indent=1, sort_keys=True)


# ------------------------------------------------------------------ builder


class StrategyBuilder(ABC):
    """ABC for strategy builders (reference ``strategy/base.py:102-117``).

    Builders are pure functions of (ModelItem, ResourceSpec) -> Strategy."""

    @abstractmethod
    def build(self, model_item, resource_spec) -> Strategy:
        ...


class StrategyCompiler:
    """Resolves a Strategy against concrete cluster devices
    (reference ``strategy/base.py:120-168`` + ``kernel/device/resolver.py``):
    prunes configs for variables that no longer exist, checks every trainable
    variable has one, and resolves device name strings. Frozen vars keep
    their configs — they may carry mp_axes storage layouts (their
    synchronizers are ignored by the lowering)."""

    def __init__(self, model_item, resource_spec):
        self._item = model_item
        self._spec = resource_spec

    def compile(self, strategy: Strategy) -> Strategy:
        from autodist_tpu.kernel.device.resolver import DeviceResolver
        resolver = DeviceResolver(self._spec)
        # keep configs for every known var (frozen vars may carry mp_axes
        # storage layouts); only require one per *trainable* var below
        known = set(self._item.var_infos)
        trainable = set(self._item.trainable_var_names)
        pruned = []
        for node in strategy.node_config:
            if node.var_name not in known:
                logging.debug("StrategyCompiler: pruning config for unknown var %s", node.var_name)
                continue
            if isinstance(node.synchronizer, PSSynchronizer) and node.synchronizer.reduction_destination:
                node.synchronizer.reduction_destination = resolver.resolve(
                    node.synchronizer.reduction_destination)
            for part in node.part_configs:
                if isinstance(part.synchronizer, PSSynchronizer) and part.synchronizer.reduction_destination:
                    part.synchronizer.reduction_destination = resolver.resolve(
                        part.synchronizer.reduction_destination)
            pruned.append(node)
        strategy.node_config = pruned
        strategy.graph_config.replicas = [resolver.resolve(r) for r in strategy.graph_config.replicas]
        # same rule the linter reports as ADT101 (analysis/rules.py) — the
        # compile path raises where lint time merely lists
        from autodist_tpu.analysis import rules as rules_lib
        missing = rules_lib.missing_trainable_configs(strategy, trainable)
        if missing:
            raise DiagnosticError(error(
                "ADT101",
                "strategy has no config for trainable vars: %s" % missing,
                var=missing[0],
                fixit="emit a VarConfig for every trainable variable"))
        return strategy
