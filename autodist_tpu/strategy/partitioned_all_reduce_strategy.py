"""Partitioned AllReduce: split each variable, then all-reduce each shard.

Analog of reference
``autodist/strategy/partitioned_all_reduce_strategy.py:71-117``: each
partitionable variable is split along axis 0 (smallest divisor >1, capped by
``num_local_replicas``) and every shard gets its own AllReduceSynchronizer —
useful for huge tensors whose single all-reduce would be bound by one flow
(reference ``:26-35``). On TPU the lowering realizes this as a
reduce-scatter + sharded weight update + all-gather (ZeRO-style), which is
the ICI-native way to split one tensor's reduction across links.
"""
from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                        Strategy, StrategyBuilder, VarConfig)
from autodist_tpu.strategy.partitioned_ps_strategy import (
    make_partition_str, smallest_divisor_shards)
from autodist_tpu.strategy.ps_strategy import replica_devices


class PartitionedAR(StrategyBuilder):
    def __init__(self, chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor", max_shards: int = 0):
        self.chunk_size = chunk_size
        self.all_reduce_spec = all_reduce_spec
        self.compressor = compressor
        self.max_shards = max_shards

    def build(self, model_item, resource_spec) -> Strategy:
        n_replicas = max(len(resource_spec.devices), 2)
        max_shards = self.max_shards or n_replicas
        nodes = []
        group_counter = 0
        for name in model_item.trainable_var_names:
            info = model_item.var_infos[name]
            dim0 = info.shape[0] if info.shape else 0
            num_shards = smallest_divisor_shards(dim0, max_shards)
            group = group_counter // max(self.chunk_size, 1)
            if num_shards <= 1:
                nodes.append(VarConfig(
                    var_name=name,
                    synchronizer=AllReduceSynchronizer(
                        spec=self.all_reduce_spec, compressor=self.compressor,
                        group=group)))
                group_counter += 1
                continue
            part_configs = []
            for shard_idx in range(num_shards):
                part_configs.append(VarConfig(
                    var_name="%s/part_%d" % (name, shard_idx),
                    synchronizer=AllReduceSynchronizer(
                        spec=self.all_reduce_spec, compressor=self.compressor,
                        group=group)))
                group_counter += 1
            nodes.append(VarConfig(
                var_name=name,
                partitioner=make_partition_str(len(info.shape), 0, num_shards),
                part_configs=part_configs))
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(replicas=replica_devices(resource_spec)))
