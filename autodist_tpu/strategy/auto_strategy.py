"""AutoStrategy — cost-model-driven strategy selection.

The reference only *describes* automatic strategy optimization
(``docs/design/rationale.rst``; its simulator is an empty stub). Here it is
implemented: build every candidate strategy from the standard builders (plus
chunk-size/compressor variants), rank them with the analytic simulator
(``simulator/simulator.py``), and return the cheapest — a pure function of
(ModelItem, ResourceSpec) like every other builder, so chief and workers
agree deterministically.
"""
from typing import List, Optional, Tuple

from autodist_tpu.strategy.base import Strategy, StrategyBuilder
from autodist_tpu.utils import logging


def default_candidates() -> List[Tuple[str, StrategyBuilder]]:
    """The full data-parallel strategy space the framework implements —
    the selector must search what the framework can do (the reference's
    AutoSync ambition). Model-parallel candidates join per-model via
    ``mp_rules`` (see :meth:`AutoStrategy.build`)."""
    from autodist_tpu.strategy.all_reduce_strategy import AllReduce
    from autodist_tpu.strategy.parallax_strategy import Parallax
    from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR
    from autodist_tpu.strategy.partitioned_ps_strategy import PartitionedPS
    from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing
    from autodist_tpu.strategy.ps_strategy import PS
    from autodist_tpu.strategy.remat import WithRemat
    return [
        # host-resident PS (no proxy: 1/HBM in exchange for PCIe per step)
        ("PS", PS()),
        ("PSLoadBalancing", PSLoadBalancing()),
        ("PartitionedPS", PartitionedPS()),
        # device-cached PS (proxy): params stay in HBM, PS owns the update
        ("PS/proxy", PS(local_proxy_variable=True)),
        # bounded staleness: hides slow-worker jitter inside the window
        ("PS/stale2", PS(staleness=2)),
        ("AllReduce/128", AllReduce(chunk_size=128)),
        ("AllReduce/512", AllReduce(chunk_size=512)),
        ("AllReduce/bf16", AllReduce(compressor="HorovodCompressor")),
        ("AllReduce/int8", AllReduce(compressor="Int8CompressorEF")),
        # rank-2 PowerSGD: 10-100x wire compression for DCN-bound clusters
        ("AllReduce/psgd2", AllReduce(compressor="PowerSGDCompressor:2")),
        ("PartitionedAR", PartitionedAR()),
        ("Parallax", Parallax()),
        ("Parallax/bf16", Parallax(compressor="HorovodCompressor")),
        ("Parallax/int8", Parallax(compressor="Int8CompressorEF")),
        # activation-memory relief: ranks behind the plain variants on
        # speed (extra recompute FLOPs) but ahead on the HBM feasibility
        # gate when ACTIVATIONS dominate — ZeRO/host-PS above relieve
        # param/optimizer memory instead; the gate picks whichever relief
        # fits and is fastest
        ("AllReduce/remat", WithRemat(AllReduce(chunk_size=512),
                                      policy="dots")),
    ]


def mp_candidates(model_item, resource_spec
                  ) -> List[Tuple[str, StrategyBuilder]]:
    """Tensor-parallel candidates enumerated from the model's registered
    ``mp_rules`` (set via ``AutoDist.build(..., mp_rules=...)`` or
    ``ModelItem(mp_rules=...)``): one TP entry per power-of-two shard
    count dividing the device count. The cost model prices their
    forward-collective traffic (mp_comm_time) and sharded storage, so
    they rank against the data-parallel family on one scale."""
    rules = getattr(model_item, "mp_rules", None)
    if not rules:
        return []
    from autodist_tpu.strategy.tensor_parallel_strategy import TensorParallel
    n_devices = len(resource_spec.devices)
    out: List[Tuple[str, StrategyBuilder]] = []
    k = 2
    while k <= n_devices and k <= 8:
        if n_devices % k == 0:
            out.append(("TensorParallel/%d" % k,
                        TensorParallel(tp_shards=k, mp_rules=rules)))
        k *= 2
    return out


class AutoStrategy(StrategyBuilder):
    def __init__(self, candidates: Optional[List[Tuple[str, StrategyBuilder]]] = None,
                 extra_candidates: Optional[List[Tuple[str, StrategyBuilder]]] = None,
                 **cost_model_kwargs):
        """``candidates`` REPLACES the default pool; ``extra_candidates``
        extends it — the hook for model-parallel entries (TensorParallel,
        SequenceParallelAR, ExpertParallel need model-specific mp_rules,
        so they cannot be defaults). The cost model prices their
        forward-collective traffic (``mp_comm_time``) and the HBM gate
        understands their sharded storage, so mp candidates rank against
        the data-parallel family on one scale."""
        self._candidates = candidates
        self._extra = list(extra_candidates or [])
        self._cm_kwargs = cost_model_kwargs
        self.last_ranking = None  # exposed for inspection/tests

    def build(self, model_item, resource_spec) -> Strategy:
        from autodist_tpu.simulator.simulator import Simulator
        candidates = (self._candidates or default_candidates()) + self._extra
        if self._candidates is None:
            # models that registered mp_rules enter the tp search space
            candidates = candidates + mp_candidates(model_item, resource_spec)
        built = []
        for label, builder in candidates:
            try:
                built.append((label, builder.build(model_item, resource_spec)))
            except Exception as e:  # noqa: BLE001 — skip inapplicable builders
                logging.debug("AutoStrategy: candidate %s failed (%s)", label, e)
        sim = Simulator(model_item, resource_spec, **self._cm_kwargs)
        ranking = sim.rank(built)
        self.last_ranking = ranking
        best = ranking[0]
        logging.info("AutoStrategy picked %s (est %.3f ms/step; next: %s)",
                     best.label, best.step_time_s * 1e3,
                     ", ".join("%s=%.3fms" % (r.label, r.step_time_s * 1e3)
                               for r in ranking[1:3]))
        return best.strategy
