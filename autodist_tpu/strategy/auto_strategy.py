"""AutoStrategy — cost-model-driven strategy selection.

The reference only *describes* automatic strategy optimization
(``docs/design/rationale.rst``; its simulator is an empty stub). Here it is
implemented: build every candidate strategy from the standard builders (plus
chunk-size/compressor variants), rank them with the analytic simulator
(``simulator/simulator.py``), and return the cheapest — a pure function of
(ModelItem, ResourceSpec) like every other builder, so chief and workers
agree deterministically.
"""
from typing import List, Optional, Tuple

from autodist_tpu.strategy.base import Strategy, StrategyBuilder
from autodist_tpu.utils import logging


def default_candidates() -> List[Tuple[str, StrategyBuilder]]:
    from autodist_tpu.strategy.all_reduce_strategy import AllReduce
    from autodist_tpu.strategy.parallax_strategy import Parallax
    from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR
    from autodist_tpu.strategy.partitioned_ps_strategy import PartitionedPS
    from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing
    from autodist_tpu.strategy.ps_strategy import PS
    from autodist_tpu.strategy.remat import WithRemat
    return [
        ("PS", PS()),
        ("PSLoadBalancing", PSLoadBalancing()),
        ("PartitionedPS", PartitionedPS()),
        ("AllReduce/128", AllReduce(chunk_size=128)),
        ("AllReduce/512", AllReduce(chunk_size=512)),
        ("AllReduce/bf16", AllReduce(compressor="HorovodCompressor")),
        ("AllReduce/int8", AllReduce(compressor="Int8CompressorEF")),
        ("PartitionedAR", PartitionedAR()),
        ("Parallax", Parallax()),
        ("Parallax/bf16", Parallax(compressor="HorovodCompressor")),
        # activation-memory relief: ranks behind the plain variants on
        # speed (extra recompute FLOPs) but ahead on the HBM feasibility
        # gate when ACTIVATIONS dominate — ZeRO/host-PS above relieve
        # param/optimizer memory instead; the gate picks whichever relief
        # fits and is fastest
        ("AllReduce/remat", WithRemat(AllReduce(chunk_size=512),
                                      policy="dots")),
    ]


class AutoStrategy(StrategyBuilder):
    def __init__(self, candidates: Optional[List[Tuple[str, StrategyBuilder]]] = None,
                 extra_candidates: Optional[List[Tuple[str, StrategyBuilder]]] = None,
                 **cost_model_kwargs):
        """``candidates`` REPLACES the default pool; ``extra_candidates``
        extends it — the hook for model-parallel entries (TensorParallel,
        SequenceParallelAR, ExpertParallel need model-specific mp_rules,
        so they cannot be defaults). The cost model prices their
        forward-collective traffic (``mp_comm_time``) and the HBM gate
        understands their sharded storage, so mp candidates rank against
        the data-parallel family on one scale."""
        self._candidates = candidates
        self._extra = list(extra_candidates or [])
        self._cm_kwargs = cost_model_kwargs
        self.last_ranking = None  # exposed for inspection/tests

    def build(self, model_item, resource_spec) -> Strategy:
        from autodist_tpu.simulator.simulator import Simulator
        candidates = (self._candidates or default_candidates()) + self._extra
        built = []
        for label, builder in candidates:
            try:
                built.append((label, builder.build(model_item, resource_spec)))
            except Exception as e:  # noqa: BLE001 — skip inapplicable builders
                logging.debug("AutoStrategy: candidate %s failed (%s)", label, e)
        sim = Simulator(model_item, resource_spec, **self._cm_kwargs)
        ranking = sim.rank(built)
        self.last_ranking = ranking
        best = ranking[0]
        logging.info("AutoStrategy picked %s (est %.3f ms/step; next: %s)",
                     best.label, best.step_time_s * 1e3,
                     ", ".join("%s=%.3fms" % (r.label, r.step_time_s * 1e3)
                               for r in ranking[1:3]))
        return best.strategy
