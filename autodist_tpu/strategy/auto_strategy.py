"""AutoStrategy — cost-model-driven strategy selection.

The reference only *describes* automatic strategy optimization
(``docs/design/rationale.rst``; its simulator is an empty stub). Here it is
implemented: build every candidate strategy from the standard builders (plus
chunk-size/compressor variants), rank them with the analytic simulator
(``simulator/simulator.py``), and return the cheapest — a pure function of
(ModelItem, ResourceSpec) like every other builder, so chief and workers
agree deterministically.
"""
from typing import List, Optional, Tuple

from autodist_tpu.strategy.base import Strategy, StrategyBuilder
from autodist_tpu.utils import logging


def default_candidates() -> List[Tuple[str, StrategyBuilder]]:
    """The full data-parallel strategy space the framework implements —
    the selector must search what the framework can do (the reference's
    AutoSync ambition). Model-parallel candidates join per-model via
    ``mp_rules`` (see :meth:`AutoStrategy.build`)."""
    from autodist_tpu.strategy.all_reduce_strategy import AllReduce
    from autodist_tpu.strategy.parallax_strategy import Parallax
    from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR
    from autodist_tpu.strategy.partitioned_ps_strategy import PartitionedPS
    from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing
    from autodist_tpu.strategy.ps_strategy import PS
    from autodist_tpu.strategy.remat import WithRemat
    from autodist_tpu.strategy.zero_sharded_strategy import ZeroSharded
    return [
        # host-resident PS (no proxy: 1/HBM in exchange for PCIe per step)
        ("PS", PS()),
        ("PSLoadBalancing", PSLoadBalancing()),
        ("PartitionedPS", PartitionedPS()),
        # device-cached PS (proxy): params stay in HBM, PS owns the update
        ("PS/proxy", PS(local_proxy_variable=True)),
        # bounded staleness: hides slow-worker jitter inside the window
        ("PS/stale2", PS(staleness=2)),
        ("AllReduce/128", AllReduce(chunk_size=128)),
        ("AllReduce/512", AllReduce(chunk_size=512)),
        ("AllReduce/bf16", AllReduce(compressor="HorovodCompressor")),
        ("AllReduce/int8", AllReduce(compressor="Int8CompressorEF")),
        # rank-2 PowerSGD: 10-100x wire compression for DCN-bound clusters
        ("AllReduce/psgd2", AllReduce(compressor="PowerSGDCompressor:2")),
        ("PartitionedAR", PartitionedAR()),
        # ZeRO-style sharded weight update: same wire as AllReduce, but
        # optimizer state is stored 1/P per chip — ranks behind plain AR
        # on launch latency, ahead on the HBM feasibility gate whenever
        # optimizer state is what does not fit
        ("ZeroSharded", ZeroSharded()),
        ("ZeroSharded/int8", ZeroSharded(wire_dtype="int8")),
        ("Parallax", Parallax()),
        ("Parallax/bf16", Parallax(compressor="HorovodCompressor")),
        ("Parallax/int8", Parallax(compressor="Int8CompressorEF")),
        # activation-memory relief: ranks behind the plain variants on
        # speed (extra recompute FLOPs) but ahead on the HBM feasibility
        # gate when ACTIVATIONS dominate — ZeRO/host-PS above relieve
        # param/optimizer memory instead; the gate picks whichever relief
        # fits and is fastest
        ("AllReduce/remat", WithRemat(AllReduce(chunk_size=512),
                                      policy="dots")),
    ]


def mp_candidates(model_item, resource_spec
                  ) -> List[Tuple[str, StrategyBuilder]]:
    """Model-parallel candidates enumerated from the model's registered
    ``mp_rules`` (set via ``AutoDist.build(..., mp_rules=...)``): the
    FAMILY comes from which mesh axes the rules reference —
    ``model`` -> TensorParallel, ``pipe`` -> PipelineParallel (every
    schedule the model's loss supports, plus composite pp x tp grids when
    both axes appear), ``expert`` -> ExpertParallel — and
    SequenceParallel joins when the model declares a shardable sequence
    dim (``mp_meta['seq_parallel']``). ``mp_meta`` also carries the
    pipeline knobs the model's loss was built with (``pp_microbatches``,
    ``pp_schedules``). The cost model prices forward-collective traffic
    (mp_comm_time), schedule bubbles, and sharded storage, so every
    family ranks against the data-parallel pool on one scale — the
    reference's AutoSync ambition over the WHOLE space
    (reference ``docs/design/rationale.rst``)."""
    from autodist_tpu import const
    rules = getattr(model_item, "mp_rules", None)
    meta = getattr(model_item, "mp_meta", None) or {}
    n_devices = len(resource_spec.devices)
    out: List[Tuple[str, StrategyBuilder]] = []

    def pow2s(limit=8):
        k = 2
        while k <= n_devices and k <= limit:
            if n_devices % k == 0:
                yield k
            k *= 2

    if rules:
        axes = {a for _, dims in rules for a in dims.values()}
        has_tp = const.MODEL_AXIS in axes
        has_pp = const.PIPELINE_AXIS in axes
        has_ep = const.EXPERT_AXIS in axes
        if has_tp and not has_pp:
            from autodist_tpu.strategy.tensor_parallel_strategy import (
                TensorParallel)
            for k in pow2s():
                out.append(("TensorParallel/%d" % k,
                            TensorParallel(tp_shards=k, mp_rules=rules)))
        if has_pp:
            from autodist_tpu.strategy.pipeline_parallel_strategy import (
                PipelineParallel)
            m = int(meta.get("pp_microbatches", 4))
            v = int(meta.get("pp_virtual", 2))
            # "pp_schedule" declares the schedule the loss was BUILT with;
            # "pp_schedules" additionally enumerates alternates the model
            # family supports — if the picker selects one the loss does
            # not implement, AutoDist.build fails loudly with a rebuild
            # instruction (the schedule is baked into the loss, so a
            # silent mismatch would price a program that never runs).
            # Alternates therefore REQUIRE the built schedule to be
            # declared too: without it the mismatch guard has nothing to
            # compare against, and the pick could silently misprice.
            alternates = meta.get("pp_schedules")
            if alternates and not meta.get("pp_schedule"):
                logging.warning(
                    "mp_meta['pp_schedules'] ignored: declare the BUILT "
                    "schedule via mp_meta['pp_schedule'] too, or the "
                    "picker could select a schedule the loss does not "
                    "implement without the build guard catching it")
                alternates = None
            schedules = list(alternates
                             or [meta.get("pp_schedule", "gpipe")])

            def pp_builder(k, sched, t=1):
                if sched == "interleaved":
                    if m % k:
                        return None  # schedule constraint: M % S == 0
                    # the interleaved loss BAKES its stage count (the
                    # degenerate trace emulates that logical layer
                    # order); only the declared pp_shards is a valid
                    # candidate — others would fail the build guard
                    declared_s = meta.get("pp_shards")
                    if declared_s is not None and k != int(declared_s):
                        return None
                    return PipelineParallel(pp_shards=k, tp_shards=t,
                                            n_microbatches=m,
                                            schedule=sched, mp_rules=rules,
                                            virtual_stages=v)
                return PipelineParallel(pp_shards=k, tp_shards=t,
                                        n_microbatches=m, schedule=sched,
                                        mp_rules=rules)

            for k in pow2s():
                for sched in schedules:
                    b = pp_builder(k, sched)
                    if b is not None:
                        out.append(("PipelineParallel/%d/%s" % (k, sched),
                                    b))
                if has_tp:
                    # composite dp x pp x tp grids (big-model/small-HBM)
                    for t in (2, 4):
                        if k * t <= n_devices and n_devices % (k * t) == 0:
                            b = pp_builder(k, schedules[0], t)
                            if b is not None:
                                out.append((
                                    "PP%d x TP%d/%s"
                                    % (k, t, schedules[0]), b))
        if has_ep:
            from autodist_tpu.strategy.expert_parallel_strategy import (
                ExpertParallel)
            for k in pow2s():
                out.append(("ExpertParallel/%d" % k,
                            ExpertParallel(ep_shards=k, mp_rules=rules)))
    if meta.get("seq_parallel"):
        from autodist_tpu.strategy.sequence_parallel_strategy import (
            SequenceParallelAR)
        attention = meta.get("sp_attention", "ring")
        for k in pow2s(4):
            out.append(("SequenceParallel/%d" % k,
                        SequenceParallelAR(seq_shards=k,
                                           attention=attention)))
    return out


class Ranking(list):
    """``Simulator.rank`` results plus build metadata: ``skipped`` lists
    the candidates whose *builders* failed (label + reason, with the ADT
    diagnostic when the failure carried one) so CLI/search output can
    show them, and ``search_trace`` carries the per-variable search's
    :class:`~autodist_tpu.search.trace.SearchTrace` when the search ran.
    A plain ``list`` everywhere else — existing callers keep working."""

    def __init__(self, results=(), skipped=None, search_trace=None):
        super().__init__(results)
        self.skipped = list(skipped or [])
        self.search_trace = search_trace


SEARCH_LABEL = "auto-search"


class AutoStrategy(StrategyBuilder):
    def __init__(self, candidates: Optional[List[Tuple[str, StrategyBuilder]]] = None,
                 extra_candidates: Optional[List[Tuple[str, StrategyBuilder]]] = None,
                 search=True,
                 **cost_model_kwargs):
        """``candidates`` REPLACES the default pool; ``extra_candidates``
        extends it — the hook for model-parallel entries (TensorParallel,
        SequenceParallelAR, ExpertParallel need model-specific mp_rules,
        so they cannot be defaults). The cost model prices their
        forward-collective traffic (``mp_comm_time``) and the HBM gate
        understands their sharded storage, so mp candidates rank against
        the data-parallel family on one scale.

        ``search`` adds the per-variable plan synthesis
        (``autodist_tpu/search/``) on top of the zoo: the zoo candidates
        seed a beam/annealing search over per-variable PS-vs-AllReduce,
        partitioning, bucketing and compressor choices, and the searched
        plan competes in the same ranking — all scored by the shared cost
        model with verify + ADT501 pruning, never compiling a candidate.
        ``True`` (the default) uses the default
        :class:`~autodist_tpu.search.drivers.SearchConfig`; pass a
        ``SearchConfig`` to tune budget/algo/seed, or ``False`` for the
        zoo-only ranking."""
        self._candidates = candidates
        self._extra = list(extra_candidates or [])
        self._search = search
        self._cm_kwargs = cost_model_kwargs
        self.last_ranking: Optional[Ranking] = None  # for inspection/tests

    def _run_search(self, model_item, resource_spec, sim, built):
        """Per-variable search seeded by the built zoo candidates; never
        fails the build — a search error falls back to the zoo ranking."""
        from autodist_tpu.search.drivers import SearchConfig, run_search
        config = self._search if isinstance(self._search, SearchConfig) \
            else None
        try:
            return run_search(model_item, resource_spec, config=config,
                              simulator=sim, extra_seeds=built)
        except Exception as e:  # noqa: BLE001 — search is an optimizer,
            # not a dependency: the zoo ranking answers without it
            logging.warning(
                "AutoStrategy: per-variable search failed (%s: %s); "
                "falling back to the zoo ranking", type(e).__name__, e)
            return None

    def build(self, model_item, resource_spec) -> Strategy:
        from autodist_tpu.simulator.simulator import Simulator
        candidates = (self._candidates or default_candidates()) + self._extra
        if self._candidates is None:
            # models that registered mp_rules enter the tp search space
            candidates = candidates + mp_candidates(model_item, resource_spec)
        built, skipped = [], []
        for label, builder in candidates:
            try:
                built.append((label, builder.build(model_item, resource_spec)))
            except Exception as e:  # noqa: BLE001 — skip inapplicable builders
                diag = getattr(e, "diagnostic", None)
                reason = (diag.format() if diag is not None
                          else "%s: %s" % (type(e).__name__, e))
                logging.warning("AutoStrategy: candidate %s failed: %s",
                                label, reason)
                skipped.append({"label": label, "reason": reason})
        sim = Simulator(model_item, resource_spec, **self._cm_kwargs)
        search_result = None
        if self._search:
            search_result = self._run_search(model_item, resource_spec,
                                             sim, built)
        pool = list(built)
        if search_result is not None and search_result.ok:
            # first in the pool: on an exact score tie the per-variable
            # plan wins (sort is stable), matching "search is the default
            # builder for unseen models"
            pool = [(SEARCH_LABEL, search_result.strategy)] + pool
        if not pool:
            raise RuntimeError(
                "AutoStrategy: no candidate strategy could be built "
                "(%d builder(s) failed: %s)"
                % (len(skipped),
                   "; ".join("%(label)s: %(reason)s" % s
                             for s in skipped[:3]) or "empty pool"))
        # drop projected-OOM candidates before they can win the ranking —
        # they would fail the pre-compile memory gate anyway (all-OOM
        # pools fall back to the unskipped ranking inside rank())
        ranking = sim.rank(pool, skip_projected_oom=True)
        self.last_ranking = Ranking(
            ranking, skipped=skipped,
            search_trace=(search_result.trace
                          if search_result is not None else None))
        best = ranking[0]
        logging.info("AutoStrategy picked %s (est %.3f ms/step; next: %s)",
                     best.label, best.step_time_s * 1e3,
                     ", ".join("%s=%.3fms" % (r.label, r.step_time_s * 1e3)
                               for r in ranking[1:3]))
        return best.strategy
