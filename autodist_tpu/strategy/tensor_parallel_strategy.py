"""Tensor-parallel strategy builder (beyond the reference).

The reference's strategy space ends at data parallelism with sharded
*storage* (``docs/design/architecture.rst:46-48``; its proto anticipated
more, ``proto/strategy.proto:36-41``). This builder adds the ``model`` mesh
axis: variables matching the model's partition rules are stored AND consumed
sharded (``VarConfig.mp_axes``), compute synchronizes itself with Megatron
psums (``parallel/tensor.py``), and the remaining variables ride the normal
AllReduce data-parallel path. Optionally composes a ``seq`` axis for
TP x SP long-context runs.
"""
import re
from typing import Dict, List, Optional, Tuple

from autodist_tpu import const
from autodist_tpu.strategy.all_reduce_strategy import AllReduce
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.utils import logging

# rule list: (regex matched against the full var name, {dim: mesh axis})
MpRules = List[Tuple[str, Dict[int, str]]]


def apply_mp_rules(strategy: Strategy, rules: MpRules) -> int:
    """Set ``mp_axes`` on every node whose var name matches a rule (first
    match wins). Returns the number of sharded vars."""
    compiled = [(re.compile(pat), mp) for pat, mp in rules]
    n = 0
    for node in strategy.node_config:
        for pat, mp in compiled:
            if pat.search(node.var_name):
                node.mp_axes = dict(mp)
                n += 1
                break
    return n


def add_frozen_nodes(strategy: Strategy, model_item) -> None:
    """Emit layout-only nodes for frozen vars so mp rules can shard their
    storage (the TP/PP/EP compute consumes local shards regardless of
    trainability). Shared by every model-parallel builder."""
    from autodist_tpu.strategy.base import VarConfig
    have = {n.var_name for n in strategy.node_config}
    for name, info in model_item.var_infos.items():
        if name not in have and not info.trainable:
            strategy.node_config.append(VarConfig(var_name=name))


class TensorParallel(AllReduce):
    """dp x tp (x sp) mesh with Megatron-sharded compute.

    ``mp_rules`` comes from the model family (e.g.
    ``models.tp_lm.tp_rules()``); unmatched variables stay replicated with
    AllReduce gradient sync. ``seq_shards`` adds sequence parallelism — the
    model must then use ring/Ulysses attention (``attention`` is carried as
    metadata the same way ``SequenceParallelAR`` does).
    """

    def __init__(self, tp_shards: int, mp_rules: MpRules,
                 seq_shards: int = 1, attention: str = "ring",
                 chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor"):
        super().__init__(chunk_size, all_reduce_spec, compressor)
        if tp_shards < 1 or seq_shards < 1:
            raise ValueError("tp_shards/seq_shards must be >= 1")
        self.tp_shards = tp_shards
        self.seq_shards = seq_shards
        self.mp_rules = list(mp_rules)
        self.attention = attention

    def build(self, model_item, resource_spec) -> Strategy:
        strategy = super().build(model_item, resource_spec)
        n_devices = len(strategy.graph_config.replicas)
        denom = self.tp_shards * self.seq_shards
        if n_devices % denom != 0:
            raise ValueError("%d devices not divisible by tp*sp=%d"
                             % (n_devices, denom))
        # axis order outer->inner: data, seq, model — the model axis gets the
        # innermost (fastest, nearest-neighbor ICI) mesh dimension, where the
        # per-layer psums live
        mesh_shape = {const.DATA_AXIS: n_devices // denom}
        if self.seq_shards > 1:
            mesh_shape[const.SEQUENCE_AXIS] = self.seq_shards
            strategy.graph_config.seq_axis = const.SEQUENCE_AXIS
        mesh_shape[const.MODEL_AXIS] = self.tp_shards
        strategy.graph_config.mesh_shape = mesh_shape
        add_frozen_nodes(strategy, model_item)
        n = apply_mp_rules(strategy, self.mp_rules)
        logging.info("TensorParallel: %d/%d vars model-sharded over %d-way "
                     "tp (mesh %s)", n, len(strategy.node_config),
                     self.tp_shards, mesh_shape)
        return strategy
