"""Unevenly-partitioned PS.

Analog of reference ``autodist/strategy/uneven_partition_ps_strategy.py``:
identical to PartitionedPS except ``get_num_shards`` picks the first
*non*-divisor of dim0 (>= 2), producing deliberately uneven shards
(reference ``:128-137``) — exercising the uneven-shard save/restore and
gradient-splitting paths.
"""
from autodist_tpu.strategy.base import (GraphConfig, PSSynchronizer, Strategy,
                                        VarConfig)
from autodist_tpu.strategy.partitioned_ps_strategy import (PartitionedPS,
                                                           make_partition_str)
from autodist_tpu.strategy.ps_strategy import reduction_devices, replica_devices


def first_non_divisor_shards(dim0: int, max_shards: int) -> int:
    if dim0 <= 2 or max_shards < 2:
        return 1
    for k in range(2, max_shards + 1):
        if dim0 % k != 0:
            return k
    return 1


def uneven_shard_sizes(dim0: int, num_shards: int):
    """Ceil-split: first shards get one extra element."""
    base, rem = divmod(dim0, num_shards)
    return [base + (1 if i < rem else 0) for i in range(num_shards)]


class UnevenPartitionedPS(PartitionedPS):
    def build(self, model_item, resource_spec) -> Strategy:
        destinations = reduction_devices(resource_spec)
        n_ps = len(destinations)
        nodes = []
        rr = 0
        for name in model_item.trainable_var_names:
            info = model_item.var_infos[name]
            dim0 = info.shape[0] if info.shape else 0
            num_shards = first_non_divisor_shards(dim0, max(n_ps, 3))
            if num_shards <= 1:
                nodes.append(VarConfig(
                    var_name=name,
                    synchronizer=PSSynchronizer(
                        reduction_destination=destinations[rr % n_ps],
                        local_replication=self._local_proxy_variable,
                        sync=self._sync, staleness=self._staleness)))
                rr += 1
                continue
            sizes = uneven_shard_sizes(dim0, num_shards)
            part_configs = []
            for shard_idx in range(num_shards):
                part_configs.append(VarConfig(
                    var_name="%s/part_%d" % (name, shard_idx),
                    synchronizer=PSSynchronizer(
                        reduction_destination=destinations[rr % n_ps],
                        local_replication=self._local_proxy_variable,
                        sync=self._sync, staleness=self._staleness)))
                rr += 1
            nodes.append(VarConfig(
                var_name=name,
                partitioner=make_partition_str(len(info.shape), 0, num_shards),
                part_configs=part_configs,
                shard_sizes=sizes))
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(replicas=replica_devices(resource_spec)))
