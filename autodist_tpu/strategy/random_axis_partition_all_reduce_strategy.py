"""Partitioned AllReduce along a randomly-chosen axis.

Analog of reference
``autodist/strategy/random_axis_partition_all_reduce_strategy.py:115-140``:
like PartitionedAR, but the split axis is chosen at random among the
partitionable axes (seeded, so chief and workers agree); sparse (embedding)
variables are forced to axis 0, since their gradient traffic is row-indexed.
"""
import random

from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                        Strategy, VarConfig)
from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR
from autodist_tpu.strategy.partitioned_ps_strategy import (
    make_partition_str, smallest_divisor_shards)
from autodist_tpu.strategy.ps_strategy import replica_devices


class RandomAxisPartitionAR(PartitionedAR):
    def __init__(self, chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor", max_shards: int = 0,
                 seed: int = 0):
        super().__init__(chunk_size, all_reduce_spec, compressor, max_shards)
        self.seed = seed

    def build(self, model_item, resource_spec) -> Strategy:
        rng = random.Random(self.seed)
        n_replicas = max(len(resource_spec.devices), 2)
        max_shards = self.max_shards or n_replicas
        nodes = []
        group_counter = 0
        for name in model_item.trainable_var_names:
            info = model_item.var_infos[name]
            # candidate axes with a usable divisor
            candidates = []
            for ax, dim in enumerate(info.shape):
                if smallest_divisor_shards(dim, max_shards) > 1:
                    candidates.append(ax)
            if info.sparse:
                candidates = [0] if 0 in candidates else []
            group = group_counter // max(self.chunk_size, 1)
            if not candidates:
                nodes.append(VarConfig(
                    var_name=name,
                    synchronizer=AllReduceSynchronizer(
                        spec=self.all_reduce_spec, compressor=self.compressor,
                        group=group)))
                group_counter += 1
                continue
            axis = rng.choice(candidates)
            num_shards = smallest_divisor_shards(info.shape[axis], max_shards)
            part_configs = []
            for shard_idx in range(num_shards):
                part_configs.append(VarConfig(
                    var_name="%s/part_%d" % (name, shard_idx),
                    synchronizer=AllReduceSynchronizer(
                        spec=self.all_reduce_spec, compressor=self.compressor,
                        group=group)))
                group_counter += 1
            nodes.append(VarConfig(
                var_name=name,
                partitioner=make_partition_str(len(info.shape), axis, num_shards),
                part_configs=part_configs))
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(replicas=replica_devices(resource_spec)))
