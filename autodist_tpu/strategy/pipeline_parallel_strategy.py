"""Pipeline-parallel strategy builder (beyond the reference).

Adds the ``pipe`` mesh axis: layer-stacked variables matching the model's
rules shard their stack dim over it (``VarConfig.mp_axes``) and the model
streams microbatches through the stages with the GPipe schedule
(``parallel/pipeline.py``). Composes with tensor parallelism (``tp_shards``)
on the innermost mesh dim — the reference's strategy space stops at data
parallelism (``docs/design/architecture.rst:46-48``).
"""
from autodist_tpu import const
from autodist_tpu.strategy.all_reduce_strategy import AllReduce
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.strategy.tensor_parallel_strategy import (
    MpRules, add_frozen_nodes, apply_mp_rules)
from autodist_tpu.utils import logging


class PipelineParallel(AllReduce):
    """pipe x dp (x tp) mesh with GPipe microbatch pipelining.

    ``mp_rules`` comes from the model family (e.g.
    ``models.pipe_lm.pp_rules(model_axis=...)``); ``n_microbatches`` is
    carried as metadata — the model's ``pipeline_apply`` call must use the
    same value.
    """

    def __init__(self, pp_shards: int, mp_rules: MpRules,
                 n_microbatches: int = 4, tp_shards: int = 1,
                 chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor",
                 schedule: str = "gpipe", virtual_stages: int = 2):
        super().__init__(chunk_size, all_reduce_spec, compressor)
        if pp_shards < 1 or tp_shards < 1:
            raise ValueError("pp_shards/tp_shards must be >= 1")
        if n_microbatches < 1:
            raise ValueError("n_microbatches must be >= 1")
        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                "schedule must be 'gpipe', '1f1b' or 'interleaved'")
        if schedule == "interleaved":
            if virtual_stages < 2:
                raise ValueError("interleaved schedule needs "
                                 "virtual_stages >= 2")
            if n_microbatches % pp_shards:
                raise ValueError(
                    "interleaved schedule needs n_microbatches (%d) "
                    "divisible by pp_shards (%d)"
                    % (n_microbatches, pp_shards))
        self.pp_shards = pp_shards
        self.tp_shards = tp_shards
        self.n_microbatches = n_microbatches
        self.schedule = schedule
        self.virtual_stages = virtual_stages if schedule == "interleaved" \
            else None
        self.mp_rules = list(mp_rules)

    def build(self, model_item, resource_spec) -> Strategy:
        strategy = super().build(model_item, resource_spec)
        n_devices = len(strategy.graph_config.replicas)
        denom = self.pp_shards * self.tp_shards
        if n_devices % denom != 0:
            raise ValueError("%d devices not divisible by pp*tp=%d"
                             % (n_devices, denom))
        # outer->inner: pipe (rank-to-rank ppermute, tolerant of distance),
        # data, model (per-layer psums want the fastest links)
        mesh_shape = {const.PIPELINE_AXIS: self.pp_shards,
                      const.DATA_AXIS: n_devices // denom}
        if self.tp_shards > 1:
            mesh_shape[const.MODEL_AXIS] = self.tp_shards
        strategy.graph_config.mesh_shape = mesh_shape
        strategy.graph_config.pp_microbatches = self.n_microbatches
        strategy.graph_config.pp_schedule = self.schedule
        strategy.graph_config.pp_virtual = self.virtual_stages
        add_frozen_nodes(strategy, model_item)
        n = apply_mp_rules(strategy, self.mp_rules)
        logging.info("PipelineParallel: %d/%d vars pipe-sharded, mesh %s, "
                     "%d microbatches, %s schedule", n,
                     len(strategy.node_config), mesh_shape,
                     self.n_microbatches, self.schedule)
        return strategy
