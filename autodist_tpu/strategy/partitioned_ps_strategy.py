"""Partitioned PS: shard each variable across parameter servers.

Analog of reference ``autodist/strategy/partitioned_ps_strategy.py:104-136``:
each partitionable variable is split along axis 0 into ``num_shards`` equal
shards (num_shards = the smallest divisor of dim0 that is >1, capped by the
number of reduction devices), shards are round-robined over the PSes, and
the strategy carries a ``partitioner`` string plus per-shard configs.
Scalars and unsplittable variables fall back to plain PS assignment.
"""
from autodist_tpu.strategy.base import (GraphConfig, PSSynchronizer, Strategy,
                                        StrategyBuilder, VarConfig)
from autodist_tpu.strategy.ps_strategy import reduction_devices, replica_devices


def smallest_divisor_shards(dim0: int, max_shards: int) -> int:
    """Smallest divisor of dim0 in (1, max_shards]; 1 when none exists."""
    if dim0 <= 1 or max_shards <= 1:
        return 1
    best = 1
    for k in range(2, max_shards + 1):
        if dim0 % k == 0:
            return k
    return best


def largest_divisor_shards(dim0: int, max_shards: int) -> int:
    """Largest divisor of dim0 that is <= max_shards (>=1)."""
    for k in range(min(dim0, max_shards), 0, -1):
        if dim0 % k == 0:
            return k
    return 1


def make_partition_str(rank: int, axis: int, num_shards: int) -> str:
    counts = ["1"] * max(rank, 1)
    counts[axis] = str(num_shards)
    return ",".join(counts)


class PartitionedPS(StrategyBuilder):
    def __init__(self, local_proxy_variable: bool = False, sync: bool = True,
                 staleness: int = 0, num_shards: int = 0,
                 require_sparse: bool = False):
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        self._num_shards_override = num_shards
        self._require_sparse = require_sparse

    def _num_shards(self, dim0: int, n_ps: int) -> int:
        if self._num_shards_override:
            return largest_divisor_shards(dim0, self._num_shards_override)
        return smallest_divisor_shards(dim0, max(n_ps, 2))

    def build(self, model_item, resource_spec) -> Strategy:
        destinations = reduction_devices(resource_spec)
        n_ps = len(destinations)
        nodes = []
        rr = 0  # round-robin pointer across all shards
        for name in model_item.trainable_var_names:
            info = model_item.var_infos[name]
            dim0 = info.shape[0] if info.shape else 0
            num_shards = self._num_shards(dim0, n_ps) if dim0 > 1 else 1
            if num_shards <= 1:
                nodes.append(VarConfig(
                    var_name=name,
                    synchronizer=PSSynchronizer(
                        reduction_destination=destinations[rr % n_ps],
                        local_replication=self._local_proxy_variable,
                        sync=self._sync, staleness=self._staleness)))
                rr += 1
                continue
            part_configs = []
            for shard_idx in range(num_shards):
                part_configs.append(VarConfig(
                    var_name="%s/part_%d" % (name, shard_idx),
                    synchronizer=PSSynchronizer(
                        reduction_destination=destinations[rr % n_ps],
                        local_replication=self._local_proxy_variable,
                        sync=self._sync, staleness=self._staleness)))
                rr += 1
            nodes.append(VarConfig(
                var_name=name,
                partitioner=make_partition_str(len(info.shape), 0, num_shards),
                part_configs=part_configs))
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(
                            replicas=replica_devices(resource_spec),
                            require_sparse=self._require_sparse))
