"""AllReduce strategy: dense gradient all-reduce across all replicas.

Analog of reference ``autodist/strategy/all_reduce_strategy.py:40-90``: every
(dense) variable gets an ``AllReduceSynchronizer``; variables are grouped in
index order into buckets of ``chunk_size`` (group id = idx // chunk_size,
reference ``:60-67``) — the reference feeds groups to TF's ScopedAllocator
pass; we feed them to our gradient-bucketing concat/all-reduce/split in
``parallel/collectives.py`` (on TPU the XLA all-reduce combiner does the
same job; explicit buckets also enable per-group compression).

Sparse (embedding) variables take the sparse all-gather path inside the
lowering, mirroring the reference's sparse branch
(``all_reduce_synchronizer.py:132-173``).
"""
from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                        Strategy, StrategyBuilder, VarConfig)
from autodist_tpu.strategy.ps_strategy import replica_devices


class AllReduce(StrategyBuilder):
    def __init__(self, chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor",
                 wire_dtype: str = "fp32", compute_dtype: str = "f32",
                 overlap: bool = False):
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        self.chunk_size = chunk_size
        self.all_reduce_spec = all_reduce_spec
        self.compressor = compressor
        # "int8": blockwise-quantized two-phase all-reduce wire (dense
        # float vars only; sparse/integer vars keep fp32 — ADT310)
        self.wire_dtype = wire_dtype
        # "bf16": managed bf16 compute tier (f32 master params/opt-state/
        # accumulation — the shape rules.verify_numerics certifies)
        self.compute_dtype = compute_dtype
        # overlap: lower gradient sync as a barrier-chained per-bucket
        # schedule (reverse layer order) instead of one epilogue; pair
        # with a small chunk_size to expose more stages to hide
        self.overlap = overlap

    def build(self, model_item, resource_spec) -> Strategy:
        from autodist_tpu.parallel.collectives import wire_quantizable
        nodes = []
        for idx, name in enumerate(model_item.trainable_var_names):
            info = model_item.var_infos.get(name)
            # dense float, >= one scale block (ADT310/311 stay un-emitted
            # by construction — same gate as the searcher's canon)
            quantizable = wire_quantizable(info, min_block=True)
            nodes.append(VarConfig(
                var_name=name,
                synchronizer=AllReduceSynchronizer(
                    spec=self.all_reduce_spec,
                    compressor=self.compressor,
                    group=idx // self.chunk_size,
                    wire_dtype=(self.wire_dtype if quantizable else "fp32"))))
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(
                            replicas=replica_devices(resource_spec),
                            compute_dtype=self.compute_dtype,
                            overlap=self.overlap))
