"""GraphTransformer — lowers a compiled Strategy to an SPMD train step.

Analog of reference ``autodist/kernel/graph_transformer.py:28-92``. The
reference's pipeline — partition variables, replicate the graph, run each
variable's synchronizer ``in_graph_apply`` then ``between_graph_apply`` —
becomes, on TPU:

1. **Partition** (``kernel/partitioner.py``): assign per-variable storage
   layouts on the mesh.
2. **Replicate** (``kernel/replicator.py``): trivial under SPMD — the data
   axis of the mesh *is* the replica set; the batch is sharded along it.
3. **Synchronize**: each variable's synchronizer contributes the gradient
   collective (bucketed/compressed psum, or reduce-scatter for partitioned
   vars) inside one ``shard_map``-wrapped, jitted step function.

Everything is traced once and compiled by XLA — the whole "transformed
graph" is a single SPMD program per process, identical across processes
because every input to this lowering (strategy bytes, mesh order, bucket
order) is deterministic.
"""
import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.kernel.partitioner import VariablePartitioner, VarLayout
from autodist_tpu.model_item import _normalize_path
from autodist_tpu.kernel.common import variable_utils
from autodist_tpu.kernel.synchronization.synchronizer import Synchronizer
from autodist_tpu.parallel import collectives
from autodist_tpu.parallel import ps as ps_lib
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.train_state import TrainState
from autodist_tpu.utils import logging


def _tree_map_layouts(f, tree, layout_tree):
    return jax.tree_util.tree_map(f, tree, layout_tree,
                                  is_leaf=lambda x: isinstance(x, VarLayout))


class ForwardProgram:
    """A compiled forward-only fetch program plus its per-leaf sharding
    classification (``DistributedStep.predict_program``).

    ``batch_mask`` mirrors the fetch tree with one bool per leaf: True
    for leaves the lowering sharded over the batch axes (per-example
    rows), False for replicated/reduced leaves. Serving's padded-row
    masking and per-request fan-out MUST consult it rather than compare
    output shapes — a replicated leaf whose leading dim happens to equal
    the bucket size would otherwise be sliced like per-example rows.

    Callable with the same ``(state, ps_vals, batch)`` signature as the
    underlying jitted function; ``_cache_size()`` exposes the jit
    cache's compiled-specialization count for the zero-recompile
    serving contract."""

    def __init__(self, fn: Callable, batch_mask):
        self.fn = fn
        self.batch_mask = batch_mask

    def __call__(self, state, ps_vals, batch):
        return self.fn(state, ps_vals, batch)

    def _cache_size(self) -> Optional[int]:
        cache_size = getattr(self.fn, "_cache_size", None)
        return cache_size() if callable(cache_size) else None


class DistributedStep:
    """The compiled distributed program (the reference's transformed
    GraphItem + WrappedSession rolled into one callable)."""

    def __init__(self, *, mesh: Mesh, step_fn: Callable, layouts: Dict[str, VarLayout],
                 layout_tree, strategy: Strategy, model_item, mesh_axis: str,
                 sync_state_init: Callable, metadata: Optional[dict] = None,
                 step_fn_nodonate: Optional[Callable] = None,
                 eval_fn: Optional[Callable] = None,
                 ps_store=None, holed_params_template=None,
                 fused_builder: Optional[Callable] = None,
                 forward_builder: Optional[Callable] = None,
                 decode_builder: Optional[Callable] = None,
                 zero_syncs: Optional[dict] = None):
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.all_axes = tuple(mesh.axis_names)
        self.seq_axis = strategy.graph_config.seq_axis
        self.seq_feed_keys = strategy.graph_config.seq_feed_keys
        self.batch_axes = tuple(strategy.graph_config.batch_axes or (mesh_axis,))
        self._step_fn = step_fn
        self._step_fn_nodonate = step_fn_nodonate or step_fn
        self._eval_fn = eval_fn
        self.layouts = layouts
        self._layout_tree = layout_tree
        self.strategy = strategy
        self.model_item = model_item
        self._sync_state_init = sync_state_init
        self.metadata = metadata or {}
        self.num_replicas = mesh.shape[mesh_axis]
        # host-offloaded PS: values + optimizer state for no-proxy PS vars
        # rest in the store (parallel/ps.py); the device state carries holes
        self.ps_store = ps_store
        self._holed_template = (holed_params_template
                                if holed_params_template is not None
                                else model_item.params)
        # fused multi-step engine: ``fused_builder(donate)`` returns a
        # jitted program scanning k microsteps over a stacked [k, ...]
        # batch (k is implicit in the input shape; XLA specializes per k)
        self._fused_builder = fused_builder
        self._fused_jits: Dict[bool, Callable] = {}
        # serving: ``forward_builder(serve_fn, donate_batch)`` lowers a
        # forward-only FETCH program (user-named per-example outputs, no
        # loss/grad/optimizer) — the inference engine's compile target;
        # jitted programs cache per (serve_fn, donate) so steady-state
        # serving re-dispatches, never re-lowers
        self._forward_builder = forward_builder
        self._predict_jits: Dict[tuple, Callable] = {}
        # decode serving: ``decode_builder(decode_fn, example_dstate)``
        # lowers ONE donated fixed-shape decode-step program (params + KV
        # caches + cursors -> next tokens + updated caches) — the
        # continuous-batching engine's compile target (serving/decode.py)
        self._decode_builder = decode_builder
        self._decode_jits: Dict[tuple, Callable] = {}
        # device-resident PS carry for the fused engine: full values +
        # per-var little-tree optimizer states, written back to the host
        # store only at sync points (flush_ps) instead of every step
        self._fused_ps_vals = None
        self._fused_ps_opt = None
        self._fused_ps_dirty = False
        # jitted-dispatch counter: one per __call__ / run_multi — the
        # honest "host round-trips per training job" number bench and the
        # fused-parity tests assert on
        self.dispatches = 0
        # static per-microstep quantized-AR wire bytes (int8 payload +
        # scale sidecar, and their fp32 equivalent) from the lowering —
        # credited to the wire.* counters at each dispatch
        self._wire_q_step = float(
            self.metadata.get("wire_quant_bytes_per_step", 0.0))
        self._wire_fp_step = float(
            self.metadata.get("wire_fp32_bytes_per_step", 0.0))
        # ZeRO-sharded update: per-variable kernels (shard math shared by
        # the lowering, the checkpoint re-shard, and the byte
        # accounting), static per-step rs/ag payloads for the zero.*
        # counters, and the projected opt-state HBM saving as a gauge
        self.zero_syncs = dict(zero_syncs or {})
        self._zero_rs_step = float(
            self.metadata.get("zero_rs_bytes_per_step", 0.0))
        self._zero_ag_step = float(
            self.metadata.get("zero_ag_bytes_per_step", 0.0))
        saved = float(self.metadata.get("zero_hbm_saved_bytes", 0.0))
        if saved:
            tel.gauge_set("zero.hbm_saved_bytes", saved)
        # overlapped gradient-sync schedule: credit the stage count once
        # per program build (the counter is pre-registered at zero, so
        # scrapers see the key either way); overlap.exposed_wait_ms
        # accrues in the runner's barrier wait when the program overlaps
        ostages = int(self.metadata.get("overlap_stages", 0))
        if self.metadata.get("overlap") and ostages:
            tel.counter_add("overlap.buckets", ostages)

    def _count_wire(self, microsteps: int = 1) -> None:
        if self._wire_q_step:
            tel.counter_add("wire.bytes_quantized",
                            self._wire_q_step * microsteps)
            tel.counter_add("wire.bytes_saved",
                            (self._wire_fp_step - self._wire_q_step)
                            * microsteps)
        if self._zero_rs_step or self._zero_ag_step:
            tel.counter_add("zero.rs_bytes", self._zero_rs_step * microsteps)
            tel.counter_add("zero.ag_bytes", self._zero_ag_step * microsteps)

    # ---------------------------------------------------------- ps data path

    @property
    def _ps_pipe(self):
        """Lazy PSPipeline (parallel/ps.py): overlaps the PS push (D2H +
        host apply) and the next pull's H2D staging with compute. None when
        there is no host-PS store or ``ADT_PS_OVERLAP=0`` (serial
        baseline)."""
        if not hasattr(self, "_ps_pipe_obj"):
            self._ps_pipe_obj = None
            if self.ps_store is not None and const.ENV.ADT_PS_OVERLAP.val:
                stale_ok = (self.ps_store.max_staleness() >= 1
                            or self.ps_store.any_async())
                self._ps_pipe_obj = ps_lib.PSPipeline(
                    self.ps_store, self.mesh, stale_ok)
        return self._ps_pipe_obj

    def pull_ps(self) -> dict:
        """Host -> device transfer of the current PS values (the per-step
        parameter read from the PS; empty when no var is host-resident).
        Public: eval loops pull once and reuse the snapshot across batches
        (``Runner.evaluate``). A dirty fused-superstep carry is written
        back to the store first, so the pull always reflects every
        microstep that ran."""
        if self.ps_store is None:
            return {}
        with tel.span("dstep.pull_ps", "dstep"):
            tel.counter_add("dstep.ps_pulls")
            self._flush_fused_ps()
            if self._ps_pipe is not None:
                return self._ps_pipe.values()
            from autodist_tpu.parallel.mesh import tree_to_mesh
            return tree_to_mesh(self.mesh, self.ps_store.pull(), P())

    # back-compat spelling (promoted to the public name above)
    _pull_ps = pull_ps

    def _push_ps(self, ps_grads: dict, ok=None) -> None:
        """Device -> host transfer of the reduced PS gradients + host-side
        optimizer apply (the PS update op). Pipelined when overlap is on.

        ``ok`` is the sentinel verdict riding the SAME dispatch the
        gradients came from (a device scalar): a bad verdict suppresses
        the push entirely — the PS never sees the poisoned gradient, and
        its optimizer state stays untouched. Reading the scalar costs
        nothing extra: the push path device_gets the gradients anyway,
        and the check runs in the pipeline's worker thread."""
        if self.ps_store is not None and ps_grads:
            if self._ps_pipe is not None:
                self._ps_pipe.submit(ps_grads, ok=ok)
            elif ok is not None and not bool(np.asarray(jax.device_get(ok))):
                tel.counter_add("sentinel.ps_suppressed")
                logging.warning("sentinel: PS push suppressed (bad verdict)")
            else:
                self.ps_store.push(ps_grads)

    @property
    def _ps_pipe_existing(self):
        """The pipeline ONLY if one is already constructed — flush/
        invalidate/close must never build a fresh pipeline (two executor
        threads + a staged pull) just to tear it down; only stepping
        (``_pull_ps`` via ``__call__``) constructs lazily."""
        return getattr(self, "_ps_pipe_obj", None)

    def flush_ps(self) -> None:
        """Wait for any in-flight pipelined push AND write back the fused
        engine's device-resident PS carry — every store read (checkpoint,
        gather, mirror digest) must see all submitted gradients applied."""
        if self.ps_store is None:
            return
        with tel.span("dstep.flush_ps", "dstep"):
            tel.counter_add("dstep.ps_flushes")
            if self._ps_pipe_existing is not None:
                self._ps_pipe_existing.flush()
            self._flush_fused_ps()

    def invalidate_ps(self) -> None:
        """Flush and discard the pipeline's staged values and the fused
        carry — call whenever the store's contents are replaced out of
        band (restore/re-init). The carry is DROPPED, not written back:
        out-of-band replacement means the store, not the carry, is now
        authoritative."""
        if self.ps_store is None:
            return
        self._fused_ps_vals = self._fused_ps_opt = None
        self._fused_ps_dirty = False
        if self._ps_pipe_existing is not None:
            self._ps_pipe_existing.invalidate()

    # ------------------------------------------------- fused multi-step

    def _ensure_fused_ps_carry(self):
        """Device-resident (values, opt-states) carry for the fused
        engine. First superstep (or first after a flush): land in-flight
        per-step pushes, then pull full values and per-var little-tree
        optimizer states from the store — ONE H2D transfer per fused run
        sequence instead of one per step."""
        if self.ps_store is None:
            return {}, {}
        if self._fused_ps_vals is None:
            with tel.span("dstep.pull_ps", "dstep", fused=True):
                tel.counter_add("dstep.ps_pulls")
                self.flush_ps()
                from autodist_tpu.parallel.mesh import tree_to_mesh
                # raw (unquantized) carry: the scan body applies the wire
                # codec per microstep itself, so the fused numerics match
                # the per-step quantized loop
                self._fused_ps_vals = tree_to_mesh(
                    self.mesh, self.ps_store.pull(wire=False), P())
                self._fused_ps_opt = tree_to_mesh(
                    self.mesh,
                    {n: self.ps_store.full_little_opt(n)
                     for n in self.ps_store.var_names}, P())
        return self._fused_ps_vals, self._fused_ps_opt

    def _flush_fused_ps(self) -> None:
        """Write the fused carry back to the host store (values + per-shard
        optimizer states) and drop it — the store is authoritative again.
        The per-step pipeline's staged pull predates the writeback, so it
        is invalidated too."""
        if not self._fused_ps_dirty:
            return
        vals, opt = self._fused_ps_vals, self._fused_ps_opt
        self._fused_ps_vals = self._fused_ps_opt = None
        self._fused_ps_dirty = False
        self.ps_store.absorb_device_state(jax.device_get(vals),
                                          jax.device_get(opt))
        if self._ps_pipe_existing is not None:
            self._ps_pipe_existing.invalidate()

    def _fused_fn(self, donate: bool = True) -> Callable:
        if self._fused_builder is None:
            raise NotImplementedError(
                "this DistributedStep was built without a fused-scan "
                "lowering path")
        if self.ps_store is not None and (
                self.ps_store.serving or self.ps_store.any_async()
                or self.ps_store.max_staleness() > 0):
            raise ValueError(
                "fused multi-step requires synchronous host-PS: async "
                "serving / staleness>0 let peers' applies land BETWEEN "
                "microsteps, which a scan compiled around a superstep-"
                "start snapshot cannot observe. Run per-step, or use "
                "sync=True staleness=0 PS (or an AllReduce strategy).")
        if (self.ps_store is not None and not self._fused_jits
                and any(p.partitioned for p in self.ps_store.plans.values())):
            # the host store applies the optimizer PER SHARD; the fused
            # device emulation applies it per FULL variable. Identical for
            # elementwise transforms (sgd/adam/...), but a shard-shape-
            # sensitive transform (per-tree norm clipping) would diverge —
            # say so once instead of silently changing numerics.
            logging.warning(
                "fused multi-step with a PARTITIONED host-PS store: the "
                "device emulation applies the optimizer per full variable "
                "while the per-step host path applies it per shard — "
                "identical for elementwise optimizers, but norm-based "
                "transforms (e.g. clip_by_global_norm) may differ from "
                "the per-step loop; verify parity for your optimizer")
        if donate not in self._fused_jits:
            self._fused_jits[donate] = self._fused_builder(donate)
        return self._fused_jits[donate]

    def multi_step(self, k: int, donate: bool = True) -> Callable:
        """The fused k-microstep program: ONE donated jitted dispatch
        running ``k`` steps under ``lax.scan`` over a stacked ``[k, ...]``
        batch. Gradient collectives, PS pull/push (device-emulated against
        the superstep-start snapshot, exact for sync PS), and optimizer
        applies all stay inside the program; metrics come back stacked
        ``[k, ...]`` once per superstep.

        Returns ``fused(state, ps_vals, ps_opt, stacked_batch) ->
        (new_state, new_ps_vals, new_ps_opt, stacked_metrics)``. Most
        callers want :meth:`run_multi`, which also manages the PS carry."""
        if k < 1:
            raise ValueError("multi_step needs k >= 1, got %d" % k)
        fn = self._fused_fn(donate)

        def fused(state, ps_vals, ps_opt, stacked_batch):
            lead = {int(np.shape(l)[0])
                    for l in jax.tree_util.tree_leaves(stacked_batch)}
            if lead and lead != {k}:
                raise ValueError(
                    "multi_step(k=%d) fed a stacked batch with leading "
                    "dim(s) %s" % (k, sorted(lead)))
            return fn(state, ps_vals, ps_opt, stacked_batch)
        return fused

    def run_multi(self, state: TrainState, stacked_batch,
                  donate: bool = True):
        """Run one superstep (k = the stacked batch's leading dim) and
        manage the PS carry: pull once before the first superstep, keep
        values/opt device-resident across supersteps, write back only at
        ``flush_ps`` sync points. Returns ``(new_state, stacked_metrics)``
        with metrics still device-resident — the caller decides when to
        pay the readback."""
        fn = self._fused_fn(donate)  # validates BEFORE any carry pull
        lead = {int(np.shape(l)[0])
                for l in jax.tree_util.tree_leaves(stacked_batch)}
        if len(lead) > 1:
            # catch ragged hand-built stacks here (the main execution
            # path), not only in the multi_step() accessor — lax.scan's
            # own shape error would be cryptic
            raise ValueError(
                "stacked batch has mismatched leading (microstep) dims %s"
                % sorted(lead))
        with tel.span("dstep.dispatch", "dstep", fused=True):
            ps_vals, ps_opt = self._ensure_fused_ps_carry()
            new_state, new_vals, new_opt, metrics = fn(
                state, ps_vals, ps_opt, stacked_batch)
            if self.ps_store is not None:
                self._fused_ps_vals, self._fused_ps_opt = new_vals, new_opt
                self._fused_ps_dirty = True
            self.dispatches += 1
            tel.counter_add("dstep.dispatches")
            self._count_wire(next(iter(lead), 1))
            return new_state, metrics

    def close_ps(self) -> None:
        """Flush the pipeline, land the fused carry, and shut the
        executors down (Runner.close); a fresh pipeline is lazily created
        if stepping resumes. The carry writeback matters here for the
        same reason the pipeline flush does: a close right after fused
        supersteps must not silently discard their PS updates."""
        if self.ps_store is None:
            return
        if self._ps_pipe_existing is not None:
            self._ps_pipe_existing.close()
            # ``del`` (not ``= None``): the lazy property only constructs a
            # pipeline when the attribute is *missing*, so assigning None
            # would pin the serial path forever after a close.
            del self._ps_pipe_obj
        self._flush_fused_ps()

    def __call__(self, state: TrainState, batch, donate: bool = True):
        """Run one step. ``donate=True`` (default) consumes ``state``'s
        buffers — callers holding their own reference to the input state must
        pass ``donate=False``."""
        fn = self._step_fn if donate else self._step_fn_nodonate
        with tel.span("dstep.dispatch", "dstep", fused=False):
            ps_vals = self.pull_ps()
            new_state, ps_grads, metrics = fn(state, ps_vals, batch)
            # sentinel-guarded programs ship the verdict in the metrics;
            # it gates the PS push (the one update that happens host-side)
            ok = (metrics["sentinel"]["ok"]
                  if isinstance(metrics, dict) and "sentinel" in metrics
                  else None)
            self._push_ps(ps_grads, ok=ok)
            self.dispatches += 1
            tel.counter_add("dstep.dispatches")
            self._count_wire()
            return new_state, metrics

    def evaluate(self, state: TrainState, batch, ps_vals=None):
        """Forward-only metrics: no grads, no optimizer, no gradient
        collectives — ~3x cheaper than a train step. ``ps_vals`` lets an
        eval LOOP pull the host-PS values once and reuse them across
        batches (no push happens between eval batches, so per-batch
        re-pulls would be pure PCIe waste — 1 GB of store-resident
        params x 100 batches is 100 GB of transfer for unchanged
        values)."""
        if ps_vals is None:
            ps_vals = self.pull_ps()
        if self._eval_fn is None:
            _, _, metrics = self._step_fn_nodonate(state, ps_vals, batch)
            return metrics
        return self._eval_fn(state, ps_vals, batch)

    def predict_program(self, serve_fn: Callable,
                        donate_batch: bool = True,
                        example_batch=None) -> Callable:
        """The compiled forward-only FETCH program behind the serving
        engine (``autodist_tpu/serving/``): derived from the same
        gather-params + fill-PS-holes path :meth:`evaluate` runs, but
        returning ``serve_fn(full_params, batch)`` — the user's named
        per-example outputs — instead of aggregate metrics. No grads, no
        optimizer, no gradient collectives.

        ``donate_batch=True`` donates the batch buffers (the one input a
        serving dispatch truly consumes — the params/state are shared
        across every request), so XLA reuses the request's own memory for
        activations; callers that keep a reference to the placed batch
        must pass ``donate_batch=False`` (``Runner.predict`` does).

        Returns ``fn(state, ps_vals, batch) -> outputs``; outputs with a
        leading (local-)batch dim come back sharded over the batch axes
        — ``Remapper.remap_fetch`` reassembles the global batch — and
        scalar outputs come back pmean-reduced like eval metrics. The
        program is cached per ``(serve_fn, donate_batch, feed
        structure)``: XLA additionally specializes per batch shape, which
        is exactly the bucketed-shape discipline serving relies on for
        zero steady-state recompiles.

        ``example_batch`` fixes the FEED STRUCTURE (serving feeds are
        usually the training batch minus its labels); defaults to the
        model item's training batch structure."""
        if self._forward_builder is None:
            raise NotImplementedError(
                "this DistributedStep was built without a forward-program "
                "lowering path (step_fn capture mode hides the forward "
                "pass) — serving needs loss_fn mode")
        treedef = jax.tree_util.tree_structure(
            example_batch if example_batch is not None
            else self.model_item.example_batch)
        key = (serve_fn, bool(donate_batch), treedef)
        if key not in self._predict_jits:
            self._predict_jits[key] = self._forward_builder(
                serve_fn, bool(donate_batch), example_batch)
        return self._predict_jits[key]

    def decode_program(self, decode_fn: Callable,
                       example_dstate) -> Callable:
        """The compiled decode-STEP program behind continuous batching
        (``autodist_tpu/serving/decode.py``): like
        :meth:`predict_program` it gathers params and fills PS holes, but
        the second operand is the engine's slot-major decode state (KV
        caches ``[slots, ...]``, per-slot token/cursor/alive) rather than
        a request feed, and the state is ALWAYS donated — the returned
        caches alias the previous step's buffers, so steady-state decode
        holds one cache allocation regardless of slot churn.

        ``example_dstate`` fixes the state's structure and (fixed!)
        shapes; the program is cached per ``(decode_fn, structure)`` and
        XLA sees exactly one shape — the zero-recompile contract the
        decode engine asserts after warmup."""
        if self._decode_builder is None:
            raise NotImplementedError(
                "this DistributedStep was built without a decode-program "
                "lowering path (step_fn capture mode hides the forward "
                "pass) — continuous-batching decode needs loss_fn mode")
        treedef = jax.tree_util.tree_structure(example_dstate)
        key = (decode_fn, treedef)
        if key not in self._decode_jits:
            self._decode_jits[key] = self._decode_builder(
                decode_fn, example_dstate)
        return self._decode_jits[key]

    def snapshot_lowered(self, state: TrainState, batch):
        """Dump the transformed program's StableHLO (the reference's
        '3-transformed' TensorBoard snapshot, ``graph_transformer.py:90``)."""
        from autodist_tpu.utils import visualization_util
        try:
            text = self.lowered_text(state, batch)
            visualization_util.log_program("3-transformed-stablehlo", text,
                                           force=True)
        except Exception as e:  # noqa: BLE001 — diagnostics must not break runs
            logging.warning("snapshot_lowered failed: %s", e)

    def _ps_avals(self, with_opt: bool = False, wire: bool = True):
        """(value avals, little-tree optimizer-state avals) for the
        host-resident PS vars — lowering inputs that must not cost a real
        pull. The opt avals (one ``optimizer.init`` trace per var) are
        only materialized when asked for — the per-step lowering path
        never consumes them. ``wire=True`` mirrors the step path's entry
        structure (quantized vars enter as their {"q", "s"} containers);
        the fused program's carry is raw f32 (``wire=False``)."""
        if self.ps_store is None:
            return {}, {}
        infos = self.model_item.var_infos
        raw_avals = {n: jax.ShapeDtypeStruct(tuple(infos[n].shape),
                                             np.dtype(infos[n].dtype))
                     for n in self.ps_store.var_names}
        opt_avals = {}
        if with_opt:
            opt_avals = {n: jax.eval_shape(
                lambda a: self.model_item.optimizer.init({"v": a}), aval)
                for n, aval in raw_avals.items()}
        ps_avals = raw_avals
        if wire:
            quant = set(self.metadata.get("ps_wire_int8", ()))
            if quant:
                from autodist_tpu.parallel import collectives
                ps_avals = {
                    n: (collectives.wire_avals(tuple(infos[n].shape))
                        if n in quant else a)
                    for n, a in raw_avals.items()}
        return ps_avals, opt_avals

    def lowered_text(self, state: TrainState, batch, fuse_steps: int = 1,
                     program: str = "train", donate: bool = False) -> str:
        """StableHLO text of the compiled step (used by snapshots, tests
        asserting on collective structure, and the static analyzers in
        ``analysis/hlo.py``/``analysis/memory.py``). PS values enter as
        avals — lowering must not cost a real pull.

        ``program="eval"`` lowers the forward-only eval program (falling
        back to the train step when no eval lowering exists, e.g. step_fn
        mode). With ``fuse_steps=k > 1``, lowers the fused k-microstep
        scan program instead; ``batch`` must then be the stacked
        ``[k, ...]`` feed (real arrays or avals). ``donate=True`` lowers
        the donated variant — the one that actually runs in steady state
        — whose entry carry aliases its outputs (what the ADT503
        donation check and honest peak-HBM estimates need)."""
        if program not in ("train", "eval"):
            raise ValueError("program must be 'train' or 'eval', got %r"
                             % (program,))
        if program == "eval":
            ps_avals, _ = self._ps_avals()
            fn = (self._eval_fn if self._eval_fn is not None
                  else self._step_fn_nodonate)
            return fn.lower(state, ps_avals, batch).as_text()
        if fuse_steps > 1:
            ps_avals, opt_avals = self._ps_avals(with_opt=True, wire=False)
            return self._fused_fn(donate=donate).lower(
                state, ps_avals, opt_avals, batch).as_text()
        ps_avals, _ = self._ps_avals()
        fn = self._step_fn if donate else self._step_fn_nodonate
        return fn.lower(state, ps_avals, batch).as_text()

    # ------------------------------------------------------------- state mgmt

    def _put(self, value, pspec: P):
        from autodist_tpu.parallel.mesh import host_to_mesh
        return host_to_mesh(self.mesh, value, pspec)

    def place_sync_state(self, sync_state):
        """Compressor state onto the mesh in its storage layout (leading
        device axis over all mesh axes) — the ONE placement rule, shared
        by init_state and the cross-topology restore's reset path."""
        return jax.tree_util.tree_map(
            lambda arr: self._put(arr, P(self.all_axes)), sync_state)

    def init_state(self, params, opt_state=None, sync_state=None) -> TrainState:
        """Shard initial params/optimizer state into storage layout: PS
        leaves go to the host store; device leaves are padded (partitioned
        vars) and placed on the mesh. ``params``/``opt_state`` arrive in the
        ORIGINAL full layout (the checkpoint layout)."""
        item = self.model_item
        self.invalidate_ps()  # re-init replaces the store's contents
        if self.ps_store is not None and not ps_lib.holes_of(params):
            # host-resident leaves: values + per-shard optimizer state
            # (an already-holed input means re-init from a live state — the
            # store then keeps its current contents)
            self.ps_store.init_params(params)
            params = ps_lib.hole_like(self._holed_template, params)
            if opt_state is not None:
                self.ps_store.load_opt_from_full(opt_state)
                holed_opt_template = jax.eval_shape(item.optimizer.init,
                                                    self._holed_template)
                opt_state = ps_lib.hole_like(holed_opt_template, opt_state)
        if self.zero_syncs and item.optimizer is not None \
                and opt_state is not None:
            # ZeRO-sharded vars have no slot in the device optimizer tree
            # (their state lives sharded in sync_state['zero']); a full
            # (checkpoint-layout) opt_state is holed down to the device
            # basis — idempotent when already holed
            basis = ps_lib.hole_out_params(self._holed_template,
                                           frozenset(self.zero_syncs))
            opt_state = ps_lib.hole_like(
                jax.eval_shape(item.optimizer.init, basis), opt_state)
        if opt_state is None:
            # step_fn mode has no framework-owned optimizer: whatever
            # optimizer state exists lives inside the user's opaque state
            opt_state = (item.optimizer.init(
                ps_lib.hole_out_params(params, frozenset(self.zero_syncs))
                if self.zero_syncs else params)
                if item.optimizer is not None else {})
        # pad + place params. Device-resident leaves stay on device the
        # whole way: jnp.pad pads in an on-device op and _put reshards
        # device-side — np.pad would download every leaf first.
        def place_var(leaf, lay: VarLayout):
            padded = False
            # already-padded leaves (state re-initialized from a live placed
            # TrainState) must not be padded a second time
            if lay.partitioned and np.shape(leaf)[lay.axis] == lay.orig_dim:
                pad = [(0, 0)] * np.ndim(leaf)
                pad[lay.axis] = (0, lay.padded_dim - lay.orig_dim)
                if isinstance(leaf, jax.Array):
                    leaf = jnp.pad(leaf, pad)
                else:
                    leaf = np.pad(np.asarray(leaf), pad)
                padded = True
            if (not padded and isinstance(leaf, jax.Array)
                    and jax.process_count() == 1):
                # the TrainState must OWN fresh buffers: the step donates
                # them, and device_put may alias the caller's buffer —
                # not only on a matching-sharding no-op but ALSO when a
                # reshard reuses the source buffer as one of the output
                # shards (observed: SingleDevice -> 8-way replicated kept
                # the source as shard 0, and donation deleted the user's
                # params). No reliable aliasing predicate exists, so copy
                # unconditionally: jnp.copy is device-side (no host trip)
                # and transient per-leaf, not a whole-tree spike. Padding
                # and the multi-process callback path already copy.
                leaf = jnp.copy(leaf)
            return self._put(leaf, lay.pspec)
        params_placed = _tree_map_layouts(place_var, params, self._layout_tree)
        # optimizer state: match each leaf to its variable's layout
        opt_layout_tree = variable_utils.map_state_layouts(
            opt_state, item.var_infos, self.layouts, VarLayout(name=""))
        opt_placed = _tree_map_layouts(place_var, opt_state, opt_layout_tree)
        if sync_state is None:
            sync_state = self._sync_state_init()
        sync_placed = self.place_sync_state(sync_state)
        step0 = self._put(np.zeros((), np.int32), P())
        return TrainState(step=step0, params=params_placed,
                          opt_state=opt_placed, sync_state=sync_placed)

    def gather_params(self, state: TrainState):
        """Params back in the original (full, unpadded) layout, on host —
        the reference's 'checkpoints load in vanilla TF' property
        (reference ``checkpoint/saver.py:50-57``). Host-resident PS values
        come straight from the store (the authoritative copy)."""
        gathered = self._gather_tree(state.params, self._layout_tree)
        if self.ps_store is not None:
            # flush the pipelined push, then apply any queued gradients this
            # process owns before reading (peers' in-flight grads are, by
            # async semantics, allowed to land after)
            self.flush_ps()
            self.ps_store.drain()
            gathered = ps_lib.fill_holes(gathered, self.ps_store.full_values())
        return gathered

    def gather_opt_state(self, state: TrainState):
        """Optimizer state in the original (full, unpadded) layout; PS
        vars' slots are reconstructed from the store's per-shard states."""
        from autodist_tpu.kernel.common import variable_utils
        layout_tree = variable_utils.map_state_layouts(
            state.opt_state, self.model_item.var_infos, self.layouts,
            VarLayout(name=""))
        gathered = self._gather_tree(state.opt_state, layout_tree)
        if self.ps_store is not None:
            # flush+drain before reading so the opt snapshot pairs with the
            # value snapshot gather_params takes (not torn across an apply)
            self.flush_ps()
            self.ps_store.drain()

            def ps_leaf(slot_path, var_name):
                if var_name in self.zero_syncs:
                    return ps_lib.PSHole(var_name)  # the zero pass fills it
                return self.ps_store.full_opt_leaf(slot_path, var_name)
            gathered = ps_lib.fill_holes_with_path(gathered, ps_leaf)
        if self.zero_syncs:
            # ZeRO-sharded slots reconstruct from the per-replica shards
            # in sync_state['zero'] (gathered host-side with the leading
            # device axis), concatenated in data-axis order — checkpoints
            # keep the reference's 'original full layout' property
            zero_host = self.gather_sync_state(state).get("zero", {})

            def zero_leaf(slot_path: str, var_name: str):
                zs = self.zero_syncs[var_name]
                little = zero_host[var_name]
                names, leaves, _ = variable_utils.flatten_named(little)
                flat = dict(zip(names, leaves))
                prefix = slot_path[: -len(var_name)].rstrip("/")
                key = (prefix + "/v") if prefix else "v"
                if key not in flat:
                    raise KeyError(
                        "sync_state['zero'] has no opt slot %r for %s"
                        % (slot_path, var_name))
                return zs.unshard_host(flat[key])
            gathered = ps_lib.fill_holes_with_path(gathered, zero_leaf)
        return gathered

    def gather_sync_state(self, state: TrainState):
        """Compressor state to host, keeping the leading device axis."""
        rep = jax.tree_util.tree_map(
            lambda _: NamedSharding(self.mesh, P()), state.sync_state)
        gathered = jax.jit(lambda s: s, out_shardings=rep)(state.sync_state)
        return jax.device_get(gathered)

    def _gather_tree(self, tree, layout_tree):
        rep = jax.tree_util.tree_map(lambda _: NamedSharding(self.mesh, P()), tree)
        gathered = jax.jit(
            lambda t: _tree_map_layouts(lambda leaf, lay: lay.unpad(leaf),
                                        t, layout_tree),
            out_shardings=rep)(tree)
        return jax.device_get(gathered)

    def shard_batch(self, batch):
        """Place a host-global batch onto the mesh, split along the data axis
        (delegates to the Remapper's validated feed path)."""
        from autodist_tpu.remapper import Remapper
        return Remapper(self.mesh, self.mesh_axis, seq_axis=self.seq_axis,
                        batch_axes=self.batch_axes,
                        seq_keys=self.seq_feed_keys).remap_feed(batch)


class GraphTransformer:
    """Builds the DistributedStep from (compiled strategy, mesh, model item)."""

    def __init__(self, compiled_strategy: Strategy, mesh: Mesh, model_item,
                 mesh_axis: str = const.DATA_AXIS, donate: bool = True,
                 sentinel=None):
        self._strategy = compiled_strategy
        self._mesh = mesh
        self._item = model_item
        # training health sentinel (runtime/sentinel.py SentinelPolicy):
        # when active, per-step health guards — global grad norm,
        # any-NaN/Inf over grads and post-update params, loss finiteness
        # — are compiled INTO the step and a bad verdict discards the
        # update in-graph; only ``grad_norm_limit`` is consumed here
        # (a trace-time constant), the rest drives the Runner's policy
        self._sentinel = sentinel
        # the data axis carries batch dim 0 and partitioned-var shards; any
        # further mesh axes (seq/...) replicate params and also reduce grads
        self._axis = mesh_axis if mesh_axis in mesh.axis_names else mesh.axis_names[0]
        self._axes = tuple(mesh.axis_names)
        self._donate = donate
        self.num_replicas = int(mesh.shape[self._axis])
        self.total_devices = int(np.prod([mesh.shape[a] for a in self._axes]))
        self._seq_axis = compiled_strategy.graph_config.seq_axis
        if self._seq_axis and self._seq_axis not in self._axes:
            raise ValueError("strategy seq_axis %r not in mesh axes %s"
                             % (self._seq_axis, self._axes))

    # ---------------------------------------------------------------- helpers

    def _replica_info(self):
        """Replication bookkeeping via the Replicator kernel (the
        reference's Partitioner -> Replicator -> Synchronizer pipeline)."""
        from autodist_tpu.kernel.replicator import Replicator
        batch_axes = tuple(
            self._strategy.graph_config.batch_axes or (self._axis,))
        return Replicator.apply(self._mesh, batch_axes, self._seq_axis,
                                self._strategy.graph_config.seq_feed_keys)

    def _build_synchronizers(self, layouts, ps_names=frozenset(),
                             sparse_wire=frozenset(),
                             zero_names=frozenset()) -> Dict[str, Synchronizer]:
        """Per-variable synchronizer kernels from strategy node configs
        (reference ``graph_transformer.py:94-130``). Host-resident PS vars
        (``ps_names``) have no in-SPMD synchronizer — their gradient leaves
        the device and the store applies the update. Sparse-wire vars sync
        via the (ids, values) all-gather path in the lowering
        (``ops/embedding.py``), not a dense collective. ZeRO-sharded vars
        (``zero_names``) own their whole update path through the
        ZeroSynchronizer kernels; a ZeroSharded node NOT in that set
        (single data replica) degrades to a plain AllReduce kernel."""
        from autodist_tpu.strategy.base import (
            AllReduceSynchronizer as ARConfig)
        syncs = {}
        for node in self._strategy.node_config:
            info = self._item.var_infos.get(node.var_name)
            if info is None:
                continue
            if node.var_name in zero_names:
                continue
            if node.var_name in sparse_wire:
                comp = getattr(node.synchronizer, "compressor",
                               "NoneCompressor")
                if comp and comp != "NoneCompressor":
                    logging.warning(
                        "var %s: compressor %s ignored — sparse-wire "
                        "gradients ship as (ids, values) pairs, already "
                        "batch-sized", node.var_name, comp)
                continue
            if node.var_name in ps_names:
                continue
            if not info.trainable:
                # frozen vars never sync (their grads are zeroed in the
                # step); their node may still carry an mp_axes layout
                continue
            if layouts[node.var_name].mp_axes:
                # model-parallel vars (resolved layout — a size-1 model axis
                # degenerates to replicated and takes the normal path) sync
                # via the complement-axes psum in the lowering, not a
                # synchronizer kernel; a configured compressor cannot apply
                # to them — say so rather than silently dropping it
                comp = getattr(node.synchronizer, "compressor", "NoneCompressor")
                if comp != "NoneCompressor":
                    logging.warning(
                        "var %s: compressor %s ignored — model-parallel "
                        "(mp_axes) gradients reduce uncompressed over the "
                        "complement axes", node.var_name, comp)
                continue
            cfg = node.synchronizer
            if cfg is None and node.part_configs:
                cfg = node.part_configs[0].synchronizer
            if cfg is None:
                raise ValueError("no synchronizer for var %s" % node.var_name)
            if cfg.kind == "ZeroSharded":
                # only reachable when the zero path is disarmed (one data
                # replica): a plain mean all-reduce is the exact same
                # update with nothing to shard
                cfg = ARConfig()
            kind = ("AllReduceSynchronizer" if cfg.kind == "AllReduce"
                    else "PSSynchronizer")
            extra = tuple(a for a in self._axes if a != self._axis)
            from autodist_tpu.parallel import mesh as mesh_lib
            syncs[node.var_name] = Synchronizer.create(
                kind, node.var_name, cfg, self.total_devices, self._axis,
                layouts[node.var_name], extra, mesh_lib.dcn_axes(self._mesh))
        return syncs

    # ------------------------------------------------------- step_fn mode

    def _transform_step_fn(self) -> DistributedStep:
        """Opaque-step lowering (``ModelItem.step_fn`` mode): the strategy
        decides STORAGE shardings only — each state leaf gets its
        ``VarLayout.pspec``, the batch splits over the data axis — and the
        user's ``step_fn(state, batch) -> (new_state, metrics)`` is jitted
        with those in/out_shardings. GSPMD inserts the collectives the
        global-semantics program implies: the gradient psum falls out of
        the batch sharding, ZeRO-style gathers out of partitioned leaf
        storage, tensor-parallel collectives out of mp-sharded weights.

        This is the analog of the reference's distribute-any-graph
        generality (reference ``tests/integration/cases/c4.py:31`` rewrites
        arbitrary captured graphs); here the escape hatch is sharding
        assignment rather than graph surgery, so the gradient-interception
        machinery (compressors, host-PS, sparse wire, pipeline schedules)
        requires loss_fn mode and is refused loudly below."""
        import dataclasses as _dc
        from autodist_tpu.runtime import faultinject as fi
        if self._sentinel is not None:
            # the opaque step hides the gradients the guards inspect —
            # the lowered program carries NO health checks (ADT420); the
            # Runner's sentinel degrades to loss-only monitoring
            logging.warning(
                "sentinel requested but step_fn capture mode lowers the "
                "program WITHOUT in-graph health guards (the opaque step "
                "hides its gradients) — detection degrades to host-side "
                "loss monitoring; use loss_fn mode for full guards")
        if fi.GradFaultPlan.from_env().rules:
            logging.warning(
                "ADT_GRAD_FAULT_PLAN ignored in step_fn capture mode — "
                "no gradient interception on the opaque path")
        item = self._item
        var_infos = item.var_infos
        layouts = VariablePartitioner.apply(
            self._strategy, var_infos, self.num_replicas, self._axis,
            mesh_axis_sizes={a: int(self._mesh.shape[a])
                             for a in self._axes})
        ps_plans = ps_lib.plan_host_ps(self._strategy, var_infos)
        if ps_plans:
            raise ValueError(
                "step_fn capture mode cannot lower host-PS strategies "
                "(vars %s): the opaque step hides the gradients the PS "
                "path intercepts. Use loss_fn mode, or an AllReduce/"
                "Partitioned-family strategy." % sorted(ps_plans))
        for node in self._strategy.node_config:
            for leaf_cfg in (node.part_configs or [node]):
                sync = leaf_cfg.synchronizer or node.synchronizer
                comp = getattr(sync, "compressor", None)
                if comp and comp != "NoneCompressor":
                    logging.warning(
                        "step_fn mode ignores compressor %s on %s — no "
                        "gradient interception on the opaque path",
                        comp, node.var_name)
                if getattr(sync, "kind", "") == "ZeroSharded":
                    logging.warning(
                        "step_fn mode ignores ZeroSharded on %s — the "
                        "opaque step owns its optimizer, so storage "
                        "stays replicated (no sharded update)",
                        node.var_name)

        # storage shardings WITHOUT padding: the user's math must see the
        # original shapes (GSPMD shards uneven dims transparently); padding
        # is loss_fn mode's explicit gather/scatter trick
        layouts = {n: (_dc.replace(l, padded_dim=l.orig_dim)
                       if l.partitioned else l)
                   for n, l in layouts.items()}
        names, _, treedef = variable_utils.flatten_named(item.params)
        layout_tree = variable_utils.unflatten_named(
            treedef, [layouts[n] for n in names])
        state_specs = _tree_map_layouts(lambda _leaf, lay: lay.pspec,
                                        item.params, layout_tree)
        rep = self._replica_info()
        batch_specs = jax.tree_util.tree_map_with_path(
            lambda path, leaf: rep.batch_spec(np.ndim(leaf),
                                              _normalize_path(path)),
            item.example_batch)

        out_aval = jax.eval_shape(item.step_fn, item.params,
                                  item.example_batch)
        if not (isinstance(out_aval, tuple) and len(out_aval) == 2):
            raise ValueError(
                "step_fn must return (new_state, metrics); got structure %s"
                % (jax.tree_util.tree_structure(out_aval),))
        want = jax.tree_util.tree_structure(item.params)
        got = jax.tree_util.tree_structure(out_aval[0])
        if got != want:
            raise ValueError(
                "step_fn's new_state structure %s does not match the state "
                "template %s" % (got, want))
        metric_specs = jax.tree_util.tree_map(lambda _: P(), out_aval[1])

        def shardings(spec_tree):
            return jax.tree_util.tree_map(
                lambda s: NamedSharding(self._mesh, s), spec_tree,
                is_leaf=lambda x: isinstance(x, P))
        rep_sh = NamedSharding(self._mesh, P())
        state_sh = TrainState(step=rep_sh, params=shardings(state_specs),
                              opt_state={}, sync_state={})
        in_sh = (state_sh, {}, shardings(batch_specs))
        out_sh = (state_sh, {}, shardings(metric_specs))

        def _step(state: TrainState, ps_vals, batch):
            del ps_vals  # no host-PS on the opaque path
            new_user, metrics = item.step_fn(state.params, batch)
            return (TrainState(step=state.step + 1, params=new_user,
                               opt_state=state.opt_state,
                               sync_state=state.sync_state), {}, metrics)

        step_fn = jax.jit(_step, in_shardings=in_sh, out_shardings=out_sh,
                          donate_argnums=(0,) if self._donate else ())
        step_fn_nodonate = (jax.jit(_step, in_shardings=in_sh,
                                    out_shardings=out_sh)
                            if self._donate else step_fn)

        def stacked(spec_tree):
            # prepend an unsharded k (microstep) dim to every leaf spec
            return jax.tree_util.tree_map(
                lambda s: NamedSharding(self._mesh, P(None, *s)), spec_tree,
                is_leaf=lambda x: isinstance(x, P))

        def fused_builder(donate: bool):
            def _multi(state: TrainState, ps_vals, ps_opt, batches):
                del ps_vals, ps_opt  # no host-PS on the opaque path

                def body(st, batch):
                    new_st, _, metrics = _step(st, {}, batch)
                    return new_st, metrics
                st, stacked_metrics = jax.lax.scan(body, state, batches)
                return st, {}, {}, stacked_metrics
            return jax.jit(
                _multi,
                in_shardings=(state_sh, {}, {}, stacked(batch_specs)),
                out_shardings=(state_sh, {}, {}, stacked(metric_specs)),
                donate_argnums=(0,) if donate else ())

        logging.info("GraphTransformer: lowered opaque step_fn over %d "
                     "replicas (%d state leaves, %d partitioned)",
                     self.num_replicas, len(layouts),
                     sum(1 for l in layouts.values() if l.partitioned))
        return DistributedStep(
            mesh=self._mesh, step_fn=step_fn,
            step_fn_nodonate=step_fn_nodonate, layouts=layouts,
            layout_tree=layout_tree, strategy=self._strategy,
            model_item=item, mesh_axis=self._axis,
            sync_state_init=lambda: {}, metadata={}, eval_fn=None,
            ps_store=None, holed_params_template=item.params,
            fused_builder=fused_builder)

    # ---------------------------------------------------------------- main

    def transform(self) -> DistributedStep:
        from autodist_tpu.utils import visualization_util
        item = self._item
        if item.loss_fn is None:
            return self._transform_step_fn()
        var_infos = item.var_infos
        if visualization_util.enabled():
            # stage 0: the user's original program (reference writes
            # '0-original' TensorBoard graphs, graph_transformer.py:62)
            visualization_util.log_jaxpr("0-original-loss", item.loss_fn,
                                         item.params, item.example_batch)
        layouts = VariablePartitioner.apply(
            self._strategy, var_infos, self.num_replicas, self._axis,
            mesh_axis_sizes={a: int(self._mesh.shape[a]) for a in self._axes})

        # Host-offloaded PS: no-proxy PS vars leave the device state entirely
        # (parallel/ps.py). Their device-side layout is moot (they enter the
        # step as replicated pulled values), so any partitioned layout the
        # partitioner assigned is dropped — host storage honors the TRUE
        # (possibly uneven) shard sizes instead of the padded device split.
        ps_plans = ps_lib.plan_host_ps(self._strategy, var_infos)
        ps_names = frozenset(ps_plans)
        # host-PS vars on the quantized wire (PSVarPlan.wire_dtype, guarded
        # to dense float by plan_host_ps): their pulled values enter the
        # step as {"q", "s"} int8+scales containers (dequantized in-graph)
        # and their reduced gradients exit the same way (dequantized at the
        # store boundary) — the PCIe wire carries ~1/4 the bytes
        ps_quant = frozenset(n for n, p in ps_plans.items()
                             if p.wire_dtype == "int8")
        if ps_plans:
            # the host store applies the optimizer PER VARIABLE (one
            # little {"v": shard} tree each). A structure-sensitive
            # optimizer (optax.multi_transform / masked wrappers) decides
            # its transform from the tree it sees — on a little tree the
            # label function resolves wrong and a variable would SILENTLY
            # train under the wrong transform. Refuse loudly instead.
            spec_repr = str(jax.tree_util.tree_structure(
                item.opt_state_spec)) if item.optimizer is not None else ""
            if any(s in spec_repr for s in (
                    "MaskedState", "PartitionState",
                    "MultiTransformState")):  # optax<0.2 name for the same
                raise ValueError(
                    "structure-sensitive optimizers (optax.multi_transform"
                    "/masked) are not supported on the host-resident PS "
                    "path: the store applies updates per variable, so "
                    "tree-structure-based labels would resolve incorrectly."
                    " Use local_proxy_variable=True (device-resident PS), "
                    "an AllReduce family strategy, or per-variable "
                    "optimizers without masking.")
        for n in ps_names:
            layouts[n] = VarLayout(name=n)
        ps_store = (ps_lib.PSStore(ps_plans, var_infos, item.optimizer)
                    if ps_plans else None)
        holed_params = (ps_lib.hole_out_params(item.params, ps_names)
                        if ps_names else item.params)

        # ----- ZeRO-sharded weight update (arXiv 2004.13336, stage 1):
        # params stay stored FULL; the gradient reduce-scatters over the
        # data axis, the optimizer applies to each replica's owned flat
        # shard against sync_state-resident sharded opt state (created
        # sharded, never materialized whole), and the update all-gathers
        # back onto the replicated params. The same invalid combinations
        # the linter reports as ADT312 raise here, so compile time and
        # lint time agree.
        from autodist_tpu.kernel.synchronization.zero_synchronizer import (
            ZeroSynchronizer)
        zero_syncs: Dict[str, ZeroSynchronizer] = {}
        zero_stride = int(np.prod(
            [self._mesh.shape[a] for a in self._axes[
                self._axes.index(self._axis) + 1:]] or [1]))
        for node in self._strategy.node_config:
            cfg = node.synchronizer
            if cfg is None or getattr(cfg, "kind", "") != "ZeroSharded":
                continue
            info = var_infos.get(node.var_name)
            if info is None or not info.trainable:
                continue
            if getattr(info, "sparse", False):
                raise ValueError(
                    "var %s: ZeroSharded on a sparse (gather-indexed) "
                    "variable — the reduce-scatter would densify its "
                    "batch-row-sized gradient to the full table every "
                    "step (ADT312); route it to PS or plain AllReduce"
                    % node.var_name)
            if node.mp_axes or node.partitioner:
                raise ValueError(
                    "var %s: ZeroSharded cannot combine with %s storage "
                    "(ADT312) — the sharded update owns the whole flat "
                    "variable" % (node.var_name,
                                  "mp_axes" if node.mp_axes
                                  else "partitioner"))
            if self.num_replicas <= 1:
                # one data replica: nothing to shard — the node degrades
                # to plain AllReduce in _build_synchronizers below
                logging.info(
                    "var %s: ZeroSharded on a single data replica "
                    "degrades to plain AllReduce sync", node.var_name)
                continue
            zero_syncs[node.var_name] = ZeroSynchronizer(
                node.var_name, cfg, tuple(info.shape), info.dtype,
                self._axis, self.num_replicas,
                tuple(a for a in self._axes if a != self._axis),
                self.total_devices, zero_stride)
        zero_names = frozenset(zero_syncs)
        # ZeRO-sharded vars have no slot in the device optimizer tree —
        # the main optimizer.update runs on the holed basis, and their
        # little-tree shard applies run against sync_state['zero']
        opt_basis = (ps_lib.hole_out_params(holed_params, zero_names)
                     if zero_names else holed_params)
        zero_basis_template = (jax.eval_shape(lambda t: t, opt_basis)
                               if zero_names else None)

        names, _, treedef = variable_utils.flatten_named(holed_params)
        layout_tree = variable_utils.unflatten_named(
            treedef, [layouts[n] for n in names])

        # Model-parallel vars (tensor/pipeline/expert sharded storage) bypass
        # the synchronizer machinery: their gradient reduces only over the
        # complement mesh axes (the forward's own collectives — psum in a
        # row-parallel matmul, ppermute in a pipeline, all_to_all in MoE —
        # already account for the model-parallel axes).
        mp_names = frozenset(n for n, l in layouts.items() if l.mp_axes)
        mp_complement = {
            n: tuple(a for a in self._axes
                     if a not in set(layouts[n].mp_axis_names))
            for n in mp_names}

        # Sparse wire path (ops/embedding.py): gather-indexed vars whose
        # lookups carry a matching name synchronize as (ids, values) pairs
        # — batch-shaped wire instead of vocab-shaped (the reference's
        # IndexedSlices all-gather, all_reduce_synchronizer.py:132-173).
        from autodist_tpu.ops import embedding as embedding_lib
        # AR sparse wire only exists ACROSS devices (it replaces the dense
        # gradient collective); on a single replica there is nothing to
        # save and the explicit scatter path only costs compile time. The
        # host-PS path keeps it regardless: (ids, values) still beats a
        # vocab-sized dense push over PCIe.
        sparse_candidates = {
            n for n, v in var_infos.items()
            if v.sparse and v.trainable
            and (n in ps_names
                 or (self.total_devices > 1
                     and not layouts[n].partitioned
                     and not layouts[n].mp_axes))}
        sparse_specs = {}
        if sparse_candidates and item.loss_fn is not None:
            loss_plain = (lambda p, b: item.loss_fn(p, b)[0]) if item.has_aux \
                else item.loss_fn
            # taps live INSIDE shard_map: discover against the per-device
            # (local) batch shape, not the host-global one. ReplicaInfo is
            # the SAME source the shard_map in_specs use below, so the tap
            # shapes cannot disagree with the actual batch split.
            rep = self._replica_info()

            def local_aval(path, leaf):
                return jax.ShapeDtypeStruct(
                    rep.local_shape(np.shape(leaf), _normalize_path(path)),
                    np.asarray(leaf).dtype
                    if not hasattr(leaf, "dtype") else leaf.dtype)
            local_batch = jax.tree_util.tree_map_with_path(
                local_aval, item.example_batch)
            discovered = set()
            # the taps/safety traces run OUTSIDE the step's shard_map but
            # the loss may use mesh collectives (ring attention, Megatron
            # psum); bind the axis names at size 1 so those traces run.
            # Size 1 — not the real sizes — because the trace feeds FULL
            # (unsharded) params: under size-1 axes "local = global", so
            # model-parallel compute (expert splits, column/row matmuls)
            # sees consistent shapes. Only SHAPES are read off these
            # traces, and batch dims are pre-divided by local_aval, so
            # axis-size-dependent VALUES (mean weights, offsets) are
            # irrelevant.
            from autodist_tpu.utils.axis_env import bound_axes
            try:
                with bound_axes():
                    sparse_specs = embedding_lib.discover(
                        loss_plain, item.params, local_batch,
                        sparse_candidates)
                discovered = set(sparse_specs)
                if sparse_specs:
                    # a table with OTHER differentiable uses (tied output
                    # embedding, weight sharing) gets a real dense gradient
                    # the sparse wire would drop — keep those dense
                    full_names, _, _ = variable_utils.flatten_named(
                        item.params)
                    with bound_axes():
                        safe = embedding_lib.safe_sparse_names(
                            loss_plain, item.params, local_batch,
                            sparse_specs, full_names)
                    tied = sorted(set(sparse_specs) - safe)
                    if tied:
                        # info, not warning: a deliberate, correct routing
                        # decision (the dense head gradient would be lost
                        # on the sparse wire), not a degradation
                        logging.info(
                            "sparse vars %s have dense gradient paths "
                            "besides their lookups (tied embeddings); "
                            "keeping them on the dense sync path", tied)
                    sparse_specs = {n: s for n, s in sparse_specs.items()
                                    if n in safe}
                # the wire only pays when the gathered (ids, values)
                # payload undercuts the dense gradient (batch << vocab);
                # small tables with large batches stay dense
                keep = {}
                for n, specs in sparse_specs.items():
                    info = var_infos[n]
                    feat = max(1, int(np.prod(info.shape[1:] or (1,))))
                    rows = sum(int(np.prod(ids_shape or (1,)))
                               for ids_shape, _d, _f, _fd in specs)
                    sparse_bytes = rows * self.total_devices * (feat + 1)
                    dense_bytes = int(info.shape[0]) * feat
                    if sparse_bytes < dense_bytes:
                        keep[n] = specs
                    else:
                        logging.debug(
                            "var %s: sparse wire (%d) >= dense (%d) "
                            "elements; keeping dense sync", n,
                            sparse_bytes, dense_bytes)
                sparse_specs = keep
            except Exception as e:  # noqa: BLE001 — discovery is best-effort
                # ... except when it must not be: an exception here silently
                # degrades every sparse var to dense sync (>10x wire on
                # embedding models). Strict when the builder demanded the
                # sparse wire (require_sparse) or under test invariants.
                if (self._strategy.graph_config.require_sparse
                        or const.ENV.ADT_IS_TESTING.val):
                    raise RuntimeError(
                        "sparse-wire discovery failed and the strategy "
                        "requires the sparse gradient path (vars: %s)"
                        % sorted(sparse_candidates)) from e
                sparse_specs = {}
                logging.warning("sparse-wire discovery failed (%s); dense "
                                "sync for all sparse vars", e)
            uncaptured = sparse_candidates - discovered
            if uncaptured:
                if self._strategy.graph_config.require_sparse:
                    raise ValueError(
                        "strategy requires the sparse gradient wire but "
                        "vars %s are not routed through "
                        "ops.embedding.embedding_lookup(name=...) — their "
                        "gradients would sync DENSE (vocab-sized wire). "
                        "Route the lookups through ops.embedding, or build "
                        "with require_sparse=False." % sorted(uncaptured))
                logging.warning(
                    "sparse vars %s not routed through "
                    "ops.embedding.embedding_lookup(name=...); their "
                    "gradients sync DENSE (vocab-sized wire)",
                    sorted(uncaptured))
        sparse_wire = frozenset(sparse_specs)

        # ----- training health sentinel + gradient fault layer
        # Guards (and injected faults) are COMPILED INTO the step: both
        # read their configuration here, at transform time, so the clean
        # path stays byte-identical when neither is active.
        from autodist_tpu.runtime import faultinject as fi
        guard = self._sentinel is not None
        grad_norm_limit = (getattr(self._sentinel, "grad_norm_limit", None)
                          if guard else None)
        grad_plan = fi.GradFaultPlan.from_env()
        if grad_plan.rules:
            unknown = sorted({r.var for r in grad_plan.rules
                              if r.var not in var_infos})
            if unknown:
                logging.warning(
                    "ADT_GRAD_FAULT_PLAN names unknown variables %s — "
                    "those rules never fire", unknown)
            on_wire = sorted({r.var for r in grad_plan.rules
                              if r.var in sparse_wire})
            if on_wire:
                logging.warning(
                    "ADT_GRAD_FAULT_PLAN targets sparse-wire vars %s: the "
                    "fault lands on the (unused) dense gradient — route "
                    "those vars dense to observe the fault", on_wire)
            logging.warning("gradient fault plan compiled into the step: %s",
                            grad_plan.describe())
        # per-var squared-norm / nonfinite-count scaling for sharded
        # storage: a leaf sharded over mesh axes of total size S is
        # replicated N/S times, so psum(local * S/N) == the global value;
        # replicated leaves (scale None) are already global on every
        # device and skip the psum entirely
        def _shard_frac(lay: VarLayout):
            axes = []
            for part in tuple(lay.pspec or ()):
                if part is None:
                    continue
                axes.extend(part if isinstance(part, (tuple, list))
                            else [part])
            prod = 1
            for a in axes:
                prod *= int(self._mesh.shape[a])
            return (prod / float(self.total_devices)) if prod > 1 else None
        shard_frac = {n: f for n, lay in layouts.items()
                      if (f := _shard_frac(lay)) is not None}
        # ZeRO-sharded gradients enter the verdict as the owned shard:
        # sharded over the data axis (replicated over any extra axes),
        # so the same local*S/N stacked-psum accounting applies
        for n in zero_names:
            shard_frac[n] = self.num_replicas / float(self.total_devices)

        syncs = self._build_synchronizers(layouts, ps_names, sparse_wire,
                                          zero_names)
        # Route unpartitioned AllReduce vars with an *active* compressor into
        # concat buckets (payload transform needs the merged vector).
        # NoneCompressor vars psum individually — XLA's all-reduce combiner
        # merges those on the wire without materializing a concat, so an
        # explicit bucket would only add two full-gradient copies.
        ar_unpart = {n: s for n, s in syncs.items()
                     if s.__class__.__name__ == "AllReduceSynchronizer"
                     and not layouts[n].partitioned
                     and n not in mp_names
                     and s.compressor.name != "NoneCompressor"}
        buckets, per_var_comp = collectives.make_buckets(ar_unpart, var_infos)
        bucketed_names = {n for b in buckets for n in b.var_names}

        # ----- sync_state initialization (host-side zeros w/ leading dev axis)
        N = self.total_devices
        def sync_state_init():
            st = {"bucket": {}, "var": {}}
            for b in buckets:
                comp = b.make_compressor()
                s = comp.state_init((b.total_size,), np.dtype(b.dtype))
                if s is not None:
                    st["bucket"][b.key] = np.broadcast_to(
                        np.asarray(s)[None], (N,) + np.asarray(s).shape).copy()
            for n, s in syncs.items():
                if n in bucketed_names or n in mp_names:
                    continue
                if layouts[n].partitioned:
                    continue  # partitioned vars reduce-scatter; no compressor state
                info = var_infos[n]
                init = s.state_init(tuple(info.shape), np.dtype(info.dtype))
                if init is not None:
                    st["var"][n] = jax.tree_util.tree_map(
                        lambda a: np.broadcast_to(
                            np.asarray(a)[None], (N,) + np.asarray(a).shape).copy(),
                        init)
            if not st["bucket"]:
                st.pop("bucket")
            if not st["var"]:
                st.pop("var")
            if zero_syncs:
                # per-replica optimizer-state shards, created sharded:
                # every replica's shard inits identically (optax inits are
                # shape functions — zeros/counters), so the leading-
                # device-axis broadcast IS the correct sharded init; the
                # full state is never materialized
                zst = {}
                for n, zs in sorted(zero_syncs.items()):
                    init = zs.opt_state_init(optimizer)
                    zst[n] = jax.tree_util.tree_map(
                        lambda a: np.broadcast_to(
                            np.asarray(a)[None],
                            (N,) + np.asarray(a).shape).copy(), init)
                st["zero"] = zst
            if guard:
                # effective-LR scale for the sentinel's escalation ladder:
                # rides the sync_state (same leading-device-axis layout as
                # the compressor states) so halving it is a host-side
                # state edit, never a recompile; updates are multiplied by
                # it in-graph — exact LR semantics for linear-in-lr optax
                # transforms (sgd, adam, ...)
                st["sentinel"] = {"lr_scale": np.ones((N,), np.float32)}
            return st

        # ----- the local (per-device) step executed under shard_map
        # gradient rematerialization (graph_config.remat): compute grads
        # through jax.checkpoint so the backward recomputes activations
        # instead of storing them — the HBM-for-FLOPs trade
        remat = self._strategy.graph_config.remat

        def remat_wrap(f):
            if not remat:
                return f
            from autodist_tpu.strategy.remat import remat_transform
            return remat_transform(remat)(f)

        # ----- managed bf16 compute tier (graph_config.compute_dtype):
        # cast f32 params and float batch leaves down INSIDE the loss, so
        # grads w.r.t. the f32 master come back f32 (the convert's
        # transpose casts up) and every gradient psum accumulates in f32;
        # cast the loss (and bf16 aux) back up so the pmean and the
        # sentinel verdict judge full-precision values — exactly the
        # shape the ADT601/602/603 numerics rules certify
        compute_dtype = (getattr(self._strategy.graph_config,
                                 "compute_dtype", "f32") or "f32")
        if compute_dtype == "bf16":
            def _cd_down(x):
                x = jnp.asarray(x)
                return (x.astype(jnp.bfloat16)
                        if x.dtype == jnp.float32 else x)

            def _cd_up(x):
                x = jnp.asarray(x)
                return (x.astype(jnp.float32)
                        if x.dtype == jnp.bfloat16 else x)

            def loss_fn_cd(params, batch):
                out = item.loss_fn(
                    jax.tree_util.tree_map(_cd_down, params),
                    jax.tree_util.tree_map(_cd_down, batch))
                if item.has_aux:
                    loss, aux = out
                    return (_cd_up(loss),
                            jax.tree_util.tree_map(_cd_up, aux))
                return _cd_up(out)
        else:
            loss_fn_cd = item.loss_fn

        grad_fn = jax.value_and_grad(remat_wrap(loss_fn_cd),
                                     has_aux=item.has_aux)
        if sparse_wire:
            def loss_with_taps(full_params, taps, batch):
                with embedding_lib.capture(taps) as cap:
                    out = loss_fn_cd(full_params, batch)
                loss, aux = (out if item.has_aux else (out, None))
                return loss, (aux, cap.ids)
            sparse_grad_fn = jax.value_and_grad(
                remat_wrap(loss_with_taps), argnums=(0, 1), has_aux=True)
        optimizer = item.optimizer
        has_aux = item.has_aux
        axis = self._axis
        all_axes = self._axes
        frozen_names = frozenset(n for n, v in var_infos.items() if not v.trainable)
        from autodist_tpu.parallel import mesh as mesh_lib
        dcn = tuple(a for a in mesh_lib.dcn_axes(self._mesh) if a in all_axes)
        ici = tuple(a for a in all_axes if a not in dcn)
        # int8 quantized rings: one ring per reduced mesh axis, in order
        ring_axes = tuple((a, int(self._mesh.shape[a])) for a in all_axes)

        # ----- communication–computation overlap (graph_config.overlap):
        # gradient sync lowers as a collective SCHEDULE
        # (collectives.GradSyncSchedule) instead of one epilogue — the
        # exact same sync units (concat buckets, per-var syncs, ZeRO
        # reduce-scatters; identical membership and math, so values stay
        # bit-identical), ordered by reverse layer position (the backward
        # sweep produces the LAST layer's gradients first) and chained
        # through optimization_barrier so XLA's all-reduce combiner cannot
        # re-merge them into one epilogue payload and the latency-hiding
        # scheduler can run each stage's collective under the remaining
        # backward compute. The optimizer apply interleaves per-bucket at
        # the dataflow level: each variable's update ops depend only on
        # its own synced gradient, so XLA schedules them as stages drain
        # rather than behind the full gradient. mp/sparse/PS collectives
        # stay outside the schedule (they are forward-coupled or leave
        # the device), and the sentinel verdict still judges the COMPLETE
        # synced gradient — it consumes every stage's output.
        overlap_req = bool(getattr(self._strategy.graph_config,
                                   "overlap", False))
        overlap_armed = overlap_req and N > 1
        if overlap_armed and ps_store is not None and (
                ps_store.max_staleness() > 0 or ps_store.any_async()):
            # stale/async PS pushes already decouple from the step clock;
            # barrier-ordering device collectives against a wire that
            # intentionally lags would pin the schedule to the slowest
            # (host) path. The searcher's canon never emits this combo —
            # disarm defensively for hand-built strategies.
            logging.warning(
                "overlap disarmed: stale/async host-PS plan — the PS wire "
                "is already decoupled from the step; remove staleness/"
                "async or drop overlap to silence this")
            overlap_armed = False
        grad_schedule = None
        if overlap_armed:
            full_names, _, _ = variable_utils.flatten_named(item.params)
            var_pos = {vn: i for i, vn in enumerate(full_names)}
            units = []
            for b in buckets:
                units.append((
                    "bucket:" + b.key, "reduce", tuple(b.var_names),
                    b.total_size,
                    "int8" if b.compressor_name.startswith("Int8")
                    else "fp32", all_axes))
            for n in sorted(syncs):
                if n in bucketed_names:
                    continue
                units.append((
                    "var:" + n, "reduce", (n,),
                    int(getattr(var_infos[n], "num_elements", 0) or 0),
                    "fp32", all_axes))
            for n in sorted(zero_names):
                units.append((
                    "zero:" + n, "reduce_scatter", (n,),
                    int(getattr(var_infos[n], "num_elements", 0) or 0),
                    zero_syncs[n].wire_dtype, (axis,)))
            # a degenerate (<= 1 stage) schedule still lowers as a
            # schedule: there is nothing to overlap, and the ADT409 lint
            # flags exactly that condition instead of silently falling
            # back to the epilogue
            grad_schedule = collectives.build_grad_sync_schedule(
                units, var_pos)

        def _health_verdict(synced, ps_grads, new_params, global_loss):
            """The in-graph sentinel verdict: global gradient L2 norm,
            nonfinite counts over the synced gradients (incl. the PS
            wire) and the post-update device params, and loss
            finiteness. Replicated quantities are already global on
            every device; sharded leaves contribute ``local * S/N``
            through ONE stacked psum (exact — see ``shard_frac``), so a
            program with no sharded storage pays no extra collective.
            Every input is replica-identical, so the ``ok`` branch is
            taken uniformly across the whole (multi-process) program."""
            zero = jnp.float32(0.0)
            local_sq, bad_g_local, bad_p_local = zero, zero, zero
            shared = [zero, zero, zero]  # sharded parts: sq, bad_g, bad_p

            def _stats(arr):
                a = jnp.asarray(arr).astype(jnp.float32)
                return (jnp.sum(jnp.square(a)),
                        jnp.sum(~jnp.isfinite(a)).astype(jnp.float32))
            for n in sorted(synced):
                v = synced[n]
                if not jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact):
                    continue
                sq, bad = _stats(v)
                f = shard_frac.get(n)
                if f is not None:
                    shared[0] += sq * f
                    shared[1] += bad * f
                else:
                    local_sq += sq
                    bad_g_local += bad
            for n in sorted(ps_grads):
                gv = ps_grads[n]
                if isinstance(gv, dict):
                    # wire-quantized PS grad: judge the dequantized image
                    # (what the store will apply). A NaN gradient poisons
                    # its block scales by construction, so the nonfinite
                    # count still fires.
                    vals = collectives.dequant_wire(
                        gv, tuple(var_infos[n].shape))
                else:
                    vals = gv[1] if isinstance(gv, tuple) else gv
                sq, bad = _stats(vals)
                local_sq += sq
                bad_g_local += bad
            p_names, p_leaves, _ = variable_utils.flatten_named(new_params)
            for n, leaf in zip(p_names, p_leaves):
                if (getattr(leaf, "dtype", None) is None
                        or not jnp.issubdtype(jnp.asarray(leaf).dtype,
                                              jnp.inexact)):
                    continue
                _, bad = _stats(leaf)
                f = shard_frac.get(n)
                if f is not None:
                    shared[2] += bad * f
                else:
                    bad_p_local += bad
            red = jnp.stack(shared)
            if N > 1 and shard_frac:
                red = jax.lax.psum(red, all_axes)
            grad_norm = jnp.sqrt(local_sq + red[0])
            bad_g = bad_g_local + red[1]
            bad_p = bad_p_local + red[2]
            ok = ((bad_g == 0) & (bad_p == 0)
                  & jnp.isfinite(global_loss) & jnp.isfinite(grad_norm))
            if grad_norm_limit is not None:
                ok = ok & (grad_norm <= jnp.float32(grad_norm_limit))
            return {"ok": ok.astype(jnp.int32), "grad_norm": grad_norm,
                    "bad_grads": bad_g, "bad_params": bad_p}

        def _ps_dewire(ps_vals):
            """Quantized PS values arrive as {"q", "s"} wire containers
            (that is what crossed PCIe); dequantize in-graph before the
            loss sees them — the device-side half of the store-boundary
            codec."""
            if not ps_quant:
                return ps_vals
            out = dict(ps_vals)
            for n in ps_quant:
                info = var_infos[n]
                out[n] = collectives.dequant_wire(
                    out[n], tuple(info.shape), np.dtype(info.dtype))
            return out

        def local_step(state: TrainState, ps_vals, batch):
            ps_vals = _ps_dewire(ps_vals)
            gathered = _tree_map_layouts(
                lambda leaf, lay: lay.gather_full(leaf), state.params, layout_tree)
            # host-resident PS values arrive pulled + replicated; fill the
            # holes so the user's loss sees the full original params tree
            full_params = (ps_lib.fill_holes(gathered, ps_vals)
                           if ps_names else gathered)
            if sparse_wire:
                taps = embedding_lib.make_taps(sparse_specs)
                (loss, (aux, ids_seen)), (grads, tap_grads) = sparse_grad_fn(
                    full_params, taps, batch)
            elif has_aux:
                (loss, aux), grads = grad_fn(full_params, batch)
            else:
                loss, grads = grad_fn(full_params, batch)
                aux = None
            g_names, g_leaves, _ = variable_utils.flatten_named(grads)
            g = dict(zip(g_names, g_leaves))
            if grad_plan.rules:
                # chaos harness: deterministic step-keyed corruption of a
                # named variable's LOCAL gradient, pre-collective — NaN
                # spreads through the psum so every replica sees (and the
                # all-reduced verdict judges) the same poisoned value
                g = fi.apply_grad_faults(grad_plan, state.step, g)

            # sparse wire: per-var (ids, values) pairs, all-gathered across
            # the mesh — batch-shaped payload instead of vocab-shaped
            sparse_pairs = {}
            for n in sorted(sparse_wire):
                flat_ids, flat_vals = embedding_lib.flatten_pairs(
                    ids_seen.get(n, []), tap_grads.get(n, []))
                if N > 1:
                    flat_ids, flat_vals = embedding_lib.gather_pairs(
                        flat_ids, flat_vals, all_axes)
                sparse_pairs[n] = (flat_ids, flat_vals / N)

            # PS gradients exit the device: mean-reduced, replicated, pushed
            # to the host store by the caller (the reference's grad push to
            # the PS accumulator, ps_synchronizer.py:556-633); sparse PS
            # vars ship the (ids, values) pair itself — the store
            # scatter-adds into each owner shard's index range
            ps_grads = {}
            for n in sorted(ps_names):
                if n in sparse_pairs:
                    ps_grads[n] = sparse_pairs[n]
                elif N == 1:
                    ps_grads[n] = g[n]
                else:
                    ps_grads[n] = jax.lax.psum(g[n], all_axes) / N
                if n in ps_quant:
                    # quantize ON DEVICE: the D2H transfer (the PS push
                    # wire) carries int8 + scales; the store dequantizes
                    # at its boundary before the optimizer apply
                    ps_grads[n] = collectives.quant_wire(ps_grads[n])

            sync_state = dict(state.sync_state) if isinstance(state.sync_state, dict) else {}
            new_bucket_state = dict(sync_state.get("bucket", {}))
            new_var_state = dict(sync_state.get("var", {}))
            synced: Dict[str, Any] = {}
            psum = lambda x: jax.lax.psum(x, all_axes)  # noqa: E731

            if N == 1:
                # single replica: gradients are already global; collectives
                # would only insert degenerate all-reduces that block fusion
                # (compressor states pass through unchanged)
                synced = {n: (jnp.zeros_like(v) if n in frozen_names else v)
                          for n, v in g.items()
                          if n not in ps_names and n not in sparse_wire}

            # model-parallel vars: mean over the complement axes only; the /N
            # (total devices) normalization is exact — shard_map AD transposes
            # the forward psum/all_to_all into a sum over the model axes, and
            # that inflation cancels against the model-axis factor in N
            # (verified numerically in tests/test_tensor_parallel.py)
            for n in (mp_names if N > 1 else ()):
                if n in frozen_names:
                    synced[n] = jnp.zeros_like(g[n])
                    continue
                comp = mp_complement[n]
                synced[n] = (jax.lax.psum(g[n], comp) if comp else g[n]) / N

            # sparse AllReduce vars: densify AFTER the wire (local
            # scatter-add of the gathered pairs — reference
            # all_reduce_synchronizer.py:132-173's conversion back)
            for n in sorted(sparse_wire):
                if n in ps_names:
                    continue
                info = var_infos[n]
                s_ids, s_vals = sparse_pairs[n]
                synced[n] = embedding_lib.scatter_add_dense(
                    s_ids, s_vals, int(info.shape[0]),
                    tuple(info.shape[1:]))

            # the three gradient-sync unit kernels, shared verbatim by the
            # epilogue and the overlapped schedule — the schedule only
            # changes WHEN each unit's collective may launch (barrier
            # chaining), never its math, so the two lowerings are
            # bit-identical (optimization_barrier is an identity op)
            def _run_zero(n, gin):
                synced[n] = zero_syncs[n].reduce_scatter(gin)
                return synced[n]

            def _run_bucket(b, gin):
                bst = new_bucket_state.get(b.key)
                bst_local = bst[0] if bst is not None else None
                bucket_psum = psum
                sched = getattr(b, "schedule", "auto")
                if (b.spec == "DCN" or sched == "hier") and dcn:
                    bucket_psum = lambda x: collectives.hierarchical_psum(  # noqa: E731
                        x, ici, dcn)
                elif sched == "rhd":
                    bucket_psum = lambda x: collectives.rhd_psum(  # noqa: E731
                        x, all_axes)
                out, nst = collectives.bucket_reduce(
                    b, gin, bst_local, bucket_psum, N, ring_axes=ring_axes)
                synced.update(out)
                if nst is not None:
                    new_bucket_state[b.key] = jnp.expand_dims(nst, 0)
                return out

            def _run_var(n, gin):
                s = syncs[n]
                vst = new_var_state.get(n)
                vst_local = jax.tree_util.tree_map(lambda a: a[0], vst) if vst is not None else None
                synced[n], nst = s.sync(gin, vst_local)
                if nst is not None:
                    new_var_state[n] = jax.tree_util.tree_map(
                        lambda a: jnp.expand_dims(a, 0), nst)
                return synced[n]

            if grad_schedule is not None:
                # overlapped schedule: stages in reverse layer order, each
                # stage's gradient inputs barrier-chained on a 1-element
                # token of the previous stage's reduced output — a real
                # data dependence that keeps the stages un-merged and
                # ordered by backward readiness (see build-time comment)
                bucket_by_key = {b.key: b for b in buckets}
                token = None
                for stg in grad_schedule.stages:
                    op = stg.ops[0]
                    kind, _, uname = op.unit.partition(":")
                    if kind == "bucket":
                        b = bucket_by_key[uname]
                        gin = {n: g[n] for n in b.var_names}
                        gin, token = collectives.barrier_chain(gin, token)
                        out = _run_bucket(b, gin)
                    elif kind == "zero":
                        (gin,), token = collectives.barrier_chain(
                            (g[uname],), token)
                        out = _run_zero(uname, gin)
                    else:
                        (gin,), token = collectives.barrier_chain(
                            (g[uname],), token)
                        out = _run_var(uname, gin)
                    token = collectives.overlap_token(out)
            else:
                # epilogue lowering: ZeRO reduce-scatters, then concat
                # buckets, then per-var syncs — one contiguous block after
                # the full backward (the pre-overlap baseline, and the
                # N == 1 / overlap-off path)
                for n in sorted(zero_names):
                    _run_zero(n, g[n])
                for b in (buckets if N > 1 else []):
                    _run_bucket(b, g)
                for n in (syncs if N > 1 else ()):
                    if n in bucketed_names or n in synced:
                        continue
                    _run_var(n, g[n])
            # non-trainable vars: zero gradient so optimizer state stays
            # clean and the value never moves; remaining unconfigured vars
            # (shouldn't happen post-compile) get a plain mean-psum
            for n in g_names:
                if n in synced or n in ps_names:
                    continue
                if n in var_infos and not var_infos[n].trainable:
                    synced[n] = jnp.zeros_like(g[n])
                else:
                    synced[n] = psum(g[n]) / N

            # device-side update covers only device-resident leaves (the
            # holed structure); PS leaves update on the host, ZeRO-sharded
            # leaves per-shard against sync_state['zero'] below
            h_names, h_leaves, h_treedef = variable_utils.flatten_named(
                state.params)
            grads_storage = variable_utils.unflatten_named(
                h_treedef, [synced[n] for n in h_names])
            if zero_names:
                grads_basis = ps_lib.hole_like(zero_basis_template,
                                               grads_storage)
                params_basis = ps_lib.hole_like(zero_basis_template,
                                                state.params)
            else:
                grads_basis, params_basis = grads_storage, state.params
            updates, new_opt = optimizer.update(
                grads_basis, state.opt_state, params_basis)
            lr_scale = (sync_state["sentinel"]["lr_scale"][0] if guard
                        else None)
            if guard:
                # sentinel escalation: effective-LR scale from sync_state
                # (local slice of the leading-device-axis layout) — the
                # zero deltas below scale pre-gather to the same value
                updates = jax.tree_util.tree_map(
                    lambda u: (u * lr_scale).astype(u.dtype), updates)
            new_zero_state = {}
            if zero_names:
                # the sharded weight update: optimizer on the owned 1/P
                # shard only (per-var little trees, the SAME per-variable
                # apply shape the host-PS store runs), then all-gather the
                # UPDATE so every replica applies the identical delta to
                # its full-precision replicated param copy
                p_map = dict(zip(h_names, h_leaves))
                zstate = sync_state["zero"]
                zero_deltas = {}
                for n in sorted(zero_names):
                    zs = zero_syncs[n]
                    opt_local = jax.tree_util.tree_map(
                        lambda a: a[0], zstate[n])
                    upd, nopt = optimizer.update(
                        {"v": synced[n]}, opt_local,
                        {"v": zs.local_shard(p_map[n])})
                    d = upd["v"]
                    if lr_scale is not None:
                        d = (d * lr_scale).astype(d.dtype)
                    zero_deltas[n] = zs.gather_update(d)
                    new_zero_state[n] = jax.tree_util.tree_map(
                        lambda a: jnp.expand_dims(a, 0), nopt)
                updates = ps_lib.fill_holes(updates, zero_deltas)
            # mask non-trainable updates (guards vs. weight decay etc.)
            if frozen_names:
                u_names, u_leaves, u_treedef = variable_utils.flatten_named(updates)
                u = [jnp.zeros_like(leaf) if n in frozen_names else leaf
                     for n, leaf in zip(u_names, u_leaves)]
                updates = variable_utils.unflatten_named(u_treedef, u)
            new_params = optax.apply_updates(state.params, updates)

            global_loss = jax.lax.pmean(loss, all_axes)
            metrics = {"loss": global_loss}
            if aux is not None:
                metrics["aux"] = jax.tree_util.tree_map(
                    lambda a: (jax.lax.pmean(a, all_axes)
                               if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact)
                               else jax.lax.pmax(a, all_axes)), aux)
            new_sync = {}
            if new_bucket_state:
                new_sync["bucket"] = new_bucket_state
            if new_var_state:
                new_sync["var"] = new_var_state
            if new_zero_state:
                new_sync["zero"] = new_zero_state
            if guard:
                new_sync["sentinel"] = sync_state["sentinel"]
                verdict = _health_verdict(synced, ps_grads, new_params,
                                          global_loss)
                metrics["sentinel"] = verdict
                # in-graph SKIP: a bad verdict discards the whole update —
                # params, optimizer state and compressor residuals carry
                # unchanged through the select, so the step costs its
                # compute but poisons nothing. The verdict's inputs are
                # all-reduced, so every replica (and every process in a
                # multi-process SPMD program) takes the same branch.
                okb = verdict["ok"].astype(bool)

                def _sel(new, old):
                    return jax.tree_util.tree_map(
                        lambda a, b: jnp.where(okb, a, b), new, old)
                new_params = _sel(new_params, state.params)
                new_opt = _sel(new_opt, state.opt_state)
                new_sync = _sel(new_sync, dict(state.sync_state)
                                if isinstance(state.sync_state, dict)
                                else state.sync_state)
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   opt_state=new_opt, sync_state=new_sync)
            return new_state, ps_grads, metrics

        # ----- spec trees for shard_map
        param_specs = _tree_map_layouts(lambda _leaf, lay: lay.pspec,
                                        holed_params, layout_tree)
        opt_state_spec = (jax.eval_shape(item.optimizer.init, opt_basis)
                          if (ps_names or zero_names)
                          else item.opt_state_spec)
        # quantized-wire PS values enter (and their grads leave) as the
        # {"q", "s"} container — both replicated, like the f32 values
        ps_specs = {n: ({"q": P(), "s": P()} if n in ps_quant else P())
                    for n in sorted(ps_names)}
        # sparse PS grads leave as (ids, values) pairs, both replicated
        ps_out_specs = {n: ((P(), P()) if n in sparse_wire else
                            {"q": P(), "s": P()} if n in ps_quant else P())
                        for n in sorted(ps_names)}
        opt_layout_tree = variable_utils.map_state_layouts(
            opt_state_spec, var_infos, layouts, VarLayout(name=""))
        opt_specs = _tree_map_layouts(lambda _leaf, lay: lay.pspec,
                                      opt_state_spec, opt_layout_tree)
        sync_specs = jax.tree_util.tree_map(lambda _: P(all_axes),
                                            sync_state_init())
        state_specs = TrainState(step=P(), params=param_specs,
                                 opt_state=opt_specs, sync_state=sync_specs)
        # replication bookkeeping (replica count, batch specs, local
        # shapes) has a single owner: the Replicator kernel
        rep = self._replica_info()
        batch_specs = jax.tree_util.tree_map_with_path(
            lambda path, leaf: rep.batch_spec(np.ndim(leaf),
                                              _normalize_path(path)),
            item.example_batch)

        # metrics out-structure from an abstract eval of the loss (may fail
        # for SP losses that need a bound axis; scalar-loss fallback)
        metric_specs = {"loss": P()}
        if has_aux:
            loss_spec = jax.eval_shape(item.loss_fn, item.params,
                                       item.example_batch)
            metric_specs["aux"] = jax.tree_util.tree_map(lambda _: P(), loss_spec[1])
        if guard:
            # the verdict rides the existing metrics readback (replicated
            # scalars): zero extra dispatches, zero extra D2H
            metric_specs["sentinel"] = {"ok": P(), "grad_norm": P(),
                                        "bad_grads": P(), "bad_params": P()}

        # forward-only metrics (Runner.evaluate): same param gather, no
        # grad/optimizer/collective-sync cost
        def local_eval(state: TrainState, ps_vals, batch):
            ps_vals = _ps_dewire(ps_vals)
            gathered = _tree_map_layouts(
                lambda leaf, lay: lay.gather_full(leaf), state.params,
                layout_tree)
            full_params = (ps_lib.fill_holes(gathered, ps_vals)
                           if ps_names else gathered)
            out = loss_fn_cd(full_params, batch)
            loss, aux = (out if has_aux else (out, None))
            metrics = {"loss": jax.lax.pmean(loss, all_axes)}
            if aux is not None:
                metrics["aux"] = jax.tree_util.tree_map(
                    lambda a: (jax.lax.pmean(a, all_axes)
                               if jnp.issubdtype(jnp.asarray(a).dtype,
                                                 jnp.inexact)
                               else jax.lax.pmax(a, all_axes)), aux)
            return metrics

        # check_vma=False: with the check on, differentiating w.r.t. a
        # replicated param auto-inserts a psum during transpose, which would
        # double-count with the synchronizers' explicit collectives — this
        # framework owns the gradient collective (compression, bucketing,
        # reduce-scatter), so the automatic one must stay off.
        sharded = jax.shard_map(
            local_step, mesh=self._mesh,
            in_specs=(state_specs, ps_specs, batch_specs),
            out_specs=(state_specs, ps_out_specs, metric_specs),
            check_vma=False)
        step_fn = jax.jit(sharded, donate_argnums=(0,) if self._donate else ())
        step_fn_nodonate = jax.jit(sharded) if self._donate else step_fn
        eval_fn = jax.jit(jax.shard_map(
            local_eval, mesh=self._mesh,
            in_specs=(state_specs, ps_specs, batch_specs),
            out_specs=metric_specs, check_vma=False))

        # ----- serving forward-only lowering (DistributedStep.
        # predict_program): the SAME per-device gather-params +
        # fill-PS-holes path the eval program runs, but returning the
        # user's ``serve_fn(full_params, batch)`` fetches. The
        # out-structure comes from an abstract eval against the
        # per-device LOCAL batch shapes (axes bound so forward-pass mesh
        # collectives trace): leaves with a leading local-batch dim ship
        # sharded over the batch axes — remap_fetch reassembles the
        # global batch — and scalar leaves reduce like eval metrics.
        serve_batch_axes = tuple(
            self._strategy.graph_config.batch_axes or (axis,))

        def forward_builder(serve_fn: Callable, donate_batch: bool,
                            serve_batch=None):
            from autodist_tpu.utils.axis_env import bound_axes
            # serving feeds are usually a SUB-structure of the training
            # batch (features only, no labels) — the program's feed specs
            # come from the serve batch's own structure, by the same
            # per-leaf rule the train step uses
            if serve_batch is None:
                serve_batch = item.example_batch
            serve_specs = jax.tree_util.tree_map_with_path(
                lambda path, leaf: rep.batch_spec(np.ndim(leaf),
                                                  _normalize_path(path)),
                serve_batch)

            def local_aval(path, leaf):
                return jax.ShapeDtypeStruct(
                    rep.local_shape(np.shape(leaf), _normalize_path(path)),
                    leaf.dtype if hasattr(leaf, "dtype")
                    else np.asarray(leaf).dtype)
            local_batch = jax.tree_util.tree_map_with_path(
                local_aval, serve_batch)
            lead = [np.shape(l)[0]
                    for l in jax.tree_util.tree_leaves(local_batch)
                    if np.ndim(l) >= 1]
            local_rows = lead[0] if lead else 0
            param_avals = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(
                    np.shape(l), l.dtype if hasattr(l, "dtype")
                    else np.asarray(l).dtype), item.params)
            with bound_axes():
                out_aval = jax.eval_shape(serve_fn, param_avals,
                                          local_batch)
            out_leaves, out_treedef = jax.tree_util.tree_flatten(out_aval)
            # P is a tuple subclass, so spec trees are built by explicit
            # unflatten (tree_map would descend INTO the specs)
            flat_specs = [
                P(serve_batch_axes)
                if (np.ndim(a) >= 1 and local_rows
                    and np.shape(a)[0] == local_rows) else P()
                for a in out_leaves]
            out_specs = jax.tree_util.tree_unflatten(out_treedef,
                                                     flat_specs)

            def local_predict(state: TrainState, ps_vals, batch):
                ps_vals = _ps_dewire(ps_vals)
                gathered = _tree_map_layouts(
                    lambda leaf, lay: lay.gather_full(leaf), state.params,
                    layout_tree)
                full_params = (ps_lib.fill_holes(gathered, ps_vals)
                               if ps_names else gathered)
                out = serve_fn(full_params, batch)
                if N > 1:
                    # non-batch (replicated-spec) leaves must actually BE
                    # replicated on exit: reduce them the way eval
                    # metrics reduce
                    leaves = out_treedef.flatten_up_to(out)
                    leaves = [
                        v if len(s) else
                        (jax.lax.pmean(v, all_axes)
                         if jnp.issubdtype(jnp.asarray(v).dtype,
                                           jnp.inexact)
                         else jax.lax.pmax(v, all_axes))
                        for v, s in zip(leaves, flat_specs)]
                    out = jax.tree_util.tree_unflatten(out_treedef, leaves)
                return out

            sharded_predict = jax.shard_map(
                local_predict, mesh=self._mesh,
                in_specs=(state_specs, ps_specs, serve_specs),
                out_specs=out_specs, check_vma=False)
            # the per-leaf batch/replicated classification travels WITH
            # the program: serving's padded-row masking and per-request
            # fan-out must follow the sharding this lowering actually
            # applied, not re-derive it from output shapes (a replicated
            # leaf whose leading dim happens to equal the bucket size
            # would otherwise be sliced like per-example rows)
            batch_mask = jax.tree_util.tree_unflatten(
                out_treedef, [len(s) > 0 for s in flat_specs])
            return ForwardProgram(
                jax.jit(sharded_predict,
                        donate_argnums=(2,) if donate_batch else ()),
                batch_mask)

        def decode_builder(decode_fn: Callable, example_dstate):
            from autodist_tpu.utils.axis_env import bound_axes
            # decode state leaves are SLOT-major, not feed-path-shaped:
            # every array leaf leads with the slot dim and shards over the
            # batch axes (the per-path rules the train feed uses — seq
            # sharding for seq_feed_keys etc. — must not apply to KV
            # caches whose second dim is the sequence)
            n_batch = int(np.prod([self._mesh.shape[a]
                                   for a in serve_batch_axes] or [1]))
            state_leaves, dstate_treedef = jax.tree_util.tree_flatten(
                example_dstate)
            for leaf in state_leaves:
                if np.ndim(leaf) >= 1 and np.shape(leaf)[0] % n_batch:
                    raise ValueError(
                        "decode slot count %d is not divisible by the "
                        "batch-axes mesh extent %d — pick slots as a "
                        "multiple of the data-parallel degree"
                        % (np.shape(leaf)[0], n_batch))
            dstate_specs = jax.tree_util.tree_unflatten(
                dstate_treedef,
                [P(serve_batch_axes) if np.ndim(l) >= 1 else P()
                 for l in state_leaves])
            local_dstate = jax.tree_util.tree_unflatten(
                dstate_treedef,
                [jax.ShapeDtypeStruct(
                    ((np.shape(l)[0] // n_batch,) + tuple(np.shape(l)[1:])
                     if np.ndim(l) >= 1 else ()),
                    l.dtype if hasattr(l, "dtype")
                    else np.asarray(l).dtype)
                 for l in state_leaves])
            local_slots = ([np.shape(l)[0] // n_batch for l in state_leaves
                            if np.ndim(l) >= 1] or [0])[0]
            param_avals = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(
                    np.shape(l), l.dtype if hasattr(l, "dtype")
                    else np.asarray(l).dtype), item.params)
            with bound_axes():
                out_aval = jax.eval_shape(decode_fn, param_avals,
                                          local_dstate)
            out_leaves, out_treedef = jax.tree_util.tree_flatten(out_aval)
            flat_specs = [
                P(serve_batch_axes)
                if (np.ndim(a) >= 1 and local_slots
                    and np.shape(a)[0] == local_slots) else P()
                for a in out_leaves]
            out_specs = jax.tree_util.tree_unflatten(out_treedef,
                                                     flat_specs)

            def local_decode(state: TrainState, ps_vals, dstate):
                ps_vals = _ps_dewire(ps_vals)
                gathered = _tree_map_layouts(
                    lambda leaf, lay: lay.gather_full(leaf), state.params,
                    layout_tree)
                full_params = (ps_lib.fill_holes(gathered, ps_vals)
                               if ps_names else gathered)
                out = decode_fn(full_params, dstate)
                if N > 1:
                    leaves = out_treedef.flatten_up_to(out)
                    leaves = [
                        v if len(s) else
                        (jax.lax.pmean(v, all_axes)
                         if jnp.issubdtype(jnp.asarray(v).dtype,
                                           jnp.inexact)
                         else jax.lax.pmax(v, all_axes))
                        for v, s in zip(leaves, flat_specs)]
                    out = jax.tree_util.tree_unflatten(out_treedef, leaves)
                return out

            sharded_decode = jax.shard_map(
                local_decode, mesh=self._mesh,
                in_specs=(state_specs, ps_specs, dstate_specs),
                out_specs=out_specs, check_vma=False)
            batch_mask = jax.tree_util.tree_unflatten(
                out_treedef, [len(s) > 0 for s in flat_specs])
            # the decode state is ALWAYS donated: the step's whole point
            # is mutating the KV cache in place, and the engine feeds the
            # previous step's output straight back in. Output shardings
            # are pinned to the slot specs: jit would otherwise
            # canonicalize them (e.g. to replicated on a 1-extent mesh),
            # and the fed-back caches would re-specialize the program —
            # one recompile per step, the exact failure this path exists
            # to rule out
            out_shardings = jax.tree_util.tree_unflatten(
                out_treedef,
                [NamedSharding(self._mesh, s) for s in flat_specs])
            return ForwardProgram(
                jax.jit(sharded_decode, donate_argnums=(2,),
                        out_shardings=out_shardings), batch_mask)

        # ----- fused multi-step lowering (DistributedStep.multi_step):
        # k microsteps under lax.scan over a stacked [k, ...] batch in ONE
        # jitted dispatch. Host-PS updates are device-emulated inside the
        # scan against the superstep-start snapshot: the SAME per-variable
        # little-tree optimizer apply the store runs on host
        # (``PSStore._apply_impl``), so sync-PS numerics match the
        # per-step loop exactly — the carry writes back at flush_ps sync
        # points instead of paying a D2H round-trip per microstep.
        ps_opt_aval = {
            n: jax.eval_shape(
                lambda a: optimizer.init({"v": a}),
                jax.ShapeDtypeStruct(tuple(var_infos[n].shape),
                                     np.dtype(var_infos[n].dtype)))
            for n in sorted(ps_names)}
        ps_opt_specs = jax.tree_util.tree_map(lambda _: P(), ps_opt_aval)
        stacked_batch_specs = jax.tree_util.tree_map(
            lambda s: P(None, *s), batch_specs,
            is_leaf=lambda x: isinstance(x, P))

        def _ps_apply_device(vals, opts, ps_grads, lr_scale=None):
            new_vals, new_opts = {}, {}
            for n in sorted(vals):
                g = ps_grads[n]
                if isinstance(g, tuple):
                    # sparse (ids, values) pair: densify exactly as the
                    # host store does before its apply (np.add.at there,
                    # scatter-add here — same sum)
                    info = var_infos[n]
                    g = embedding_lib.scatter_add_dense(
                        g[0], g[1], int(info.shape[0]),
                        tuple(info.shape[1:]))
                updates, nopt = optimizer.update(
                    {"v": g}, opts[n], {"v": vals[n]})
                if lr_scale is not None:
                    # mirror of PSStore.update_scale on the host path
                    updates = jax.tree_util.tree_map(
                        lambda u: (u * lr_scale).astype(u.dtype), updates)
                new_vals[n] = optax.apply_updates({"v": vals[n]}, updates)["v"]
                new_opts[n] = nopt
            return new_vals, new_opts

        def local_multi(state: TrainState, ps_vals, ps_opt, batches):
            def body(carry, batch):
                st, vals, opts = carry
                # quantized-wire emulation: the carry holds EXACT f32
                # values (like the host store), so each microstep applies
                # the same codec the per-step wire pays — values round-trip
                # quantize->dequantize before the loss (the pull wire) and
                # the reduced gradient round-trips before the emulated
                # apply (the push wire). Fused numerics therefore match
                # the per-step quantized loop, while the actual host wire
                # is crossed once per superstep instead of once per step.
                wire_vals = {n: (collectives.quant_wire(v)
                                 if n in ps_quant else v)
                             for n, v in vals.items()}
                new_st, ps_grads, metrics = local_step(st, wire_vals, batch)
                if ps_quant:
                    ps_grads = {
                        n: (collectives.dequant_wire(
                            g, tuple(var_infos[n].shape),
                            np.dtype(var_infos[n].dtype))
                            if isinstance(g, dict) else g)
                        for n, g in ps_grads.items()}
                if ps_names:
                    scale = (st.sync_state["sentinel"]["lr_scale"][0]
                             if guard else None)
                    new_vals, new_opts = _ps_apply_device(vals, opts,
                                                          ps_grads, scale)
                    if guard:
                        # the microstep's verdict gates the device-
                        # emulated PS apply exactly like it gates the
                        # per-step host push: a bad microstep's PS update
                        # is discarded, the carry flows on unchanged
                        okb = metrics["sentinel"]["ok"].astype(bool)
                        sel = lambda a, b: jnp.where(okb, a, b)  # noqa: E731
                        new_vals = jax.tree_util.tree_map(sel, new_vals,
                                                          vals)
                        new_opts = jax.tree_util.tree_map(sel, new_opts,
                                                          opts)
                    vals, opts = new_vals, new_opts
                return (new_st, vals, opts), metrics
            (st, vals, opts), stacked_metrics = jax.lax.scan(
                body, (state, ps_vals, ps_opt), batches)
            return st, vals, opts, stacked_metrics

        # the fused carry holds RAW f32 PS values (the store's exact
        # copy); only the per-step path's entry values are wire-form
        ps_raw_specs = {n: P() for n in sorted(ps_names)}

        def fused_builder(donate: bool):
            sharded_multi = jax.shard_map(
                local_multi, mesh=self._mesh,
                in_specs=(state_specs, ps_raw_specs, ps_opt_specs,
                          stacked_batch_specs),
                out_specs=(state_specs, ps_raw_specs, ps_opt_specs,
                           metric_specs),
                check_vma=False)
            return jax.jit(sharded_multi,
                           donate_argnums=(0, 1, 2) if donate else ())

        ps_syncs = [s for s in syncs.values()
                    if s.__class__.__name__ == "PSSynchronizer"]
        # static per-microstep AR wire accounting for the quantized
        # buckets: payload bytes (int8 body + f32 scale sidecar) vs the
        # full-width bytes the same payload would have cost — bumped into
        # the wire.* telemetry counters once per dispatch (x k fused), so
        # the measured reduction is visible without any D2H. The SAME
        # formula prices the cost model and the drift tests
        # (collectives.int8_wire_payload_bytes).
        wire_q_step = wire_fp_step = 0.0
        if N > 1:
            for b in buckets:
                if b.compressor_name in ("Int8Compressor",
                                         "Int8CompressorEF"):
                    q_b, f_b = collectives.int8_wire_payload_bytes(
                        b.total_size, np.dtype(b.dtype).itemsize)
                    wire_q_step += q_b
                    wire_fp_step += f_b
        # ZeRO-sharded static accounting: per-step rs/ag payload bytes
        # (zero.rs_bytes / zero.ag_bytes counters — same formula the cost
        # model prices) and the projected per-chip opt-state saving
        # ((P-1)/P of each zero var's share of the full optimizer state —
        # the zero.hbm_saved_bytes gauge, and what the ADT501 plan gate
        # stops charging)
        zero_rs_step = sum(zs.rs_payload_bytes()
                           for zs in zero_syncs.values())
        zero_ag_step = sum(zs.ag_payload_bytes()
                           for zs in zero_syncs.values())
        zero_saved = 0.0
        if zero_syncs and item.optimizer is not None:
            opt_total = float(sum(
                int(np.prod(tuple(l.shape) or (1,)))
                * np.dtype(l.dtype).itemsize
                for l in jax.tree_util.tree_leaves(item.opt_state_spec)))
            params_total = float(item.total_bytes()) or 1.0
            zero_saved = sum(
                opt_total * var_infos[n].byte_size / params_total
                * (self.num_replicas - 1) / self.num_replicas
                for n in zero_names)
        metadata = {
            # proxied (device-cached) PS vars keep a single destination;
            # host-resident plans carry one owner per shard
            "ps_assignments": dict(
                {s.var_name: s.reduction_destination for s in ps_syncs},
                **{n: list(p.destinations) for n, p in ps_plans.items()}),
            "ps_host_resident": sorted(ps_names),
            "ps_wire_int8": sorted(ps_quant),
            "sparse_wire": sorted(sparse_wire),
            "buckets": [b.key for b in buckets],
            "per_var_compressors": per_var_comp,
            "wire_quant_bytes_per_step": wire_q_step,
            "wire_fp32_bytes_per_step": wire_fp_step,
            "zero_sharded": sorted(zero_names),
            "zero_wire_int8": sorted(n for n, zs in zero_syncs.items()
                                     if zs.wire_dtype == "int8"),
            "zero_rs_bytes_per_step": zero_rs_step,
            "zero_ag_bytes_per_step": zero_ag_step,
            "zero_hbm_saved_bytes": zero_saved,
            # staleness window for the runner's cross-process pacing
            "staleness": max(
                [s.staleness for s in ps_syncs]
                + [ps_store.max_staleness() if ps_store else 0]),
            "async": (any(not s.sync_mode for s in ps_syncs)
                      or (ps_store.any_async() if ps_store else False)),
            # health guards compiled into the program? (the ADT420 lint
            # and the Runner's policy both consult this)
            "sentinel_guards": guard,
            # "f32" | "bf16" — the compute tier this program lowered with
            # (f32 master params/opt-state/accumulation either way; the
            # ADT60x numerics lints and step_stats report it)
            "compute_dtype": compute_dtype,
            "grad_fault_plan": grad_plan.describe(),
            # communication–computation overlap: did gradient sync lower
            # as a barrier-chained schedule (vs the single epilogue)?
            # Consumed by the ADT409 lint, the drift report, and the
            # overlap.* telemetry; ``overlap_stages`` is the schedule's
            # stage count (the bucket-size knob's observable)
            "overlap": grad_schedule is not None,
            "overlap_requested": overlap_req,
            "overlap_stages": (grad_schedule.num_stages
                               if grad_schedule is not None else 0),
            "overlap_schedule": (grad_schedule.describe()
                                 if grad_schedule is not None else ""),
        }
        logging.info("GraphTransformer: lowered %d vars (%d partitioned, "
                     "%d host-PS-resident, %d ZeRO-sharded, %d buckets%s) "
                     "over %d replicas",
                     len(layouts),
                     sum(1 for l in layouts.values() if l.partitioned),
                     len(ps_names), len(zero_names), len(buckets),
                     (", overlap x%d stages" % grad_schedule.num_stages
                      if grad_schedule is not None else ""), N)
        return DistributedStep(
            mesh=self._mesh, step_fn=step_fn, step_fn_nodonate=step_fn_nodonate,
            layouts=layouts, layout_tree=layout_tree, strategy=self._strategy,
            model_item=item, mesh_axis=axis, sync_state_init=sync_state_init,
            metadata=metadata, eval_fn=eval_fn, ps_store=ps_store,
            holed_params_template=holed_params,
            fused_builder=fused_builder, forward_builder=forward_builder,
            decode_builder=decode_builder,
            zero_syncs=zero_syncs)
