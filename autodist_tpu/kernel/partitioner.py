"""Variable partitioner — sharded storage layouts for variables + optimizer state.

Analog of reference ``autodist/kernel/partitioner.py:153-714``
(``VariablePartitioner``). The reference deletes each variable from the
GraphDef, recreates it as a ``PartitionedVariable``, splits gradients with
``tf.slice`` / index-range masking, and rebuilds the optimizer slot
variables per shard. On TPU none of that surgery exists: a partitioned
variable is simply stored with a sharded layout over the mesh, the gradient
is split by a ``reduce-scatter`` (each device receives exactly its shard of
the summed gradient — the fusion of the reference's "split grads" +
"aggregate grads" steps into one ICI-native collective), and optimizer state
shards by matching state leaves to their variable
(``kernel/common/variable_utils.py:match_state_to_var`` — replacing the
reference's optimizer-scope rebuild at ``partitioner.py:376-426``).

XLA requires static uniform shard shapes, so runtime storage pads the split
axis to a multiple of the mesh axis size (ceil-split). Strategy-level shard
counts and uneven ``shard_sizes`` are preserved as metadata and honored in
the checkpoint layout (``checkpoint/saver.py``), which saves in the
*original* unpartitioned layout regardless — the reference's key property
(``checkpoint/saver.py:50-57``).
"""
import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.kernel.kernel import Kernel
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.utils import logging


@dataclasses.dataclass(frozen=True)
class VarLayout:
    """Storage layout of one variable on the mesh.

    Two orthogonal sharding mechanisms:

    - ``partitioned`` (the reference's ``PartitionedVariable``): storage
      sharded over the data axis; compute all-gathers the full value and the
      gradient comes back via reduce-scatter (ZeRO-style).
    - ``mp_axes`` (dim -> mesh axis, beyond the reference): model-parallel
      storage for tensor/pipeline/expert parallelism; compute consumes the
      LOCAL shard directly, and gradients reduce only over the *complement*
      mesh axes.
    """
    name: str
    partitioned: bool = False
    axis: int = 0                 # split axis
    num_shards: int = 1           # strategy-level shard count (metadata)
    orig_dim: int = 0             # original size of the split axis
    padded_dim: int = 0           # padded size (multiple of mesh axis size)
    mesh_axis: str = const.DATA_AXIS
    shard_sizes: Optional[Tuple[int, ...]] = None  # uneven metadata
    mp_axes: Tuple[Tuple[int, str], ...] = ()      # ((dim, mesh_axis), ...)

    @property
    def mp_axis_names(self) -> Tuple[str, ...]:
        return tuple(a for _, a in self.mp_axes)

    @property
    def pspec(self) -> P:
        ndims = [self.axis] if self.partitioned else []
        ndims += [d for d, _ in self.mp_axes]
        if not ndims:
            return P()
        spec = [None] * (max(ndims) + 1)
        if self.partitioned:
            spec[self.axis] = self.mesh_axis
        for d, a in self.mp_axes:
            if spec[d] is not None:
                raise ValueError("var %s: dim %d sharded by both %s and %s"
                                 % (self.name, d, spec[d], a))
            spec[d] = a
        return P(*spec)

    def pad(self, arr: jax.Array) -> jax.Array:
        """Zero-pad the split axis to ``padded_dim`` (full-array form)."""
        if not self.partitioned or self.padded_dim == self.orig_dim:
            return arr
        pad_widths = [(0, 0)] * arr.ndim
        pad_widths[self.axis] = (0, self.padded_dim - self.orig_dim)
        return jnp.pad(arr, pad_widths)

    def unpad(self, arr: jax.Array) -> jax.Array:
        if not self.partitioned or self.padded_dim == self.orig_dim:
            return arr
        return jax.lax.slice_in_dim(arr, 0, self.orig_dim, axis=self.axis)

    # ---- inside-shard_map helpers ----

    def gather_full(self, local: jax.Array) -> jax.Array:
        """all-gather the data-axis shard into the full (unpadded) array.
        ``mp_axes`` shards are NOT gathered — model-parallel compute consumes
        the local shard."""
        if not self.partitioned:
            return local
        full = jax.lax.all_gather(local, self.mesh_axis, axis=self.axis, tiled=True)
        return self.unpad(full)

    def reduce_scatter_grad(self, grad_full: jax.Array) -> jax.Array:
        """Pad + reduce-scatter the full gradient: each device gets the summed
        gradient for its own shard (sum, not mean — caller normalizes)."""
        if not self.partitioned:
            raise ValueError("reduce_scatter_grad on unpartitioned var %s" % self.name)
        padded = self.pad(grad_full)
        return jax.lax.psum_scatter(padded, self.mesh_axis,
                                    scatter_dimension=self.axis, tiled=True)


class VariablePartitioner(Kernel):
    """Computes ``{var_name: VarLayout}`` from a compiled Strategy.

    Variables whose strategy node has a ``partitioner`` string get a
    partitioned layout over the mesh's data axis; everything else is
    replicated. (Reference entry point: ``kernel/partitioner.py:181-229``.)
    """

    def __init__(self, key, strategy: Strategy, var_infos, mesh_axis_size: int,
                 mesh_axis: str = const.DATA_AXIS,
                 mesh_axis_sizes: Optional[Dict[str, int]] = None):
        super().__init__(key)
        self._strategy = strategy
        self._var_infos = var_infos
        self._axis_size = mesh_axis_size
        self._mesh_axis = mesh_axis
        self._mesh_axis_sizes = mesh_axis_sizes or {mesh_axis: mesh_axis_size}

    def _mp_layout(self, node, info) -> VarLayout:
        """Model-parallel storage layout from a VarConfig.mp_axes spec.
        Requires exact divisibility (no padding: the consuming compute is
        written against the local shard shape). Validation runs through
        the SAME rule functions the plan linter reports as ADT205/206/207
        (``analysis/rules.py``), so compile-time raises exactly what lint
        time would have listed."""
        from autodist_tpu.analysis.diagnostics import DiagnosticError, Severity
        from autodist_tpu.analysis.rules import check_mp_axes_node
        bad = [d for d in check_mp_axes_node(node.var_name, node.mp_axes,
                                             tuple(info.shape),
                                             self._mesh_axis_sizes)
               if d.severity >= Severity.ERROR]
        if bad:
            raise DiagnosticError(bad[0])
        mp = []
        for dim, ax_name in sorted(node.mp_axes.items()):
            size = self._mesh_axis_sizes.get(ax_name)
            if size > 1:
                mp.append((dim, ax_name))
        if node.partitioner is not None:
            logging.warning("var %s: mp_axes and partitioner both set; "
                            "mp_axes wins (ZeRO+MP on one var unsupported)",
                            node.var_name)
        return VarLayout(name=node.var_name, mp_axes=tuple(mp))

    def _apply(self) -> Dict[str, VarLayout]:
        layouts: Dict[str, VarLayout] = {}
        for node in self._strategy.node_config:
            info = self._var_infos.get(node.var_name)
            if info is None:
                continue
            if node.mp_axes:
                layouts[node.var_name] = self._mp_layout(node, info)
                continue
            axis = node.partition_axis
            if node.partitioner is None or axis is None or self._axis_size <= 1:
                layouts[node.var_name] = VarLayout(name=node.var_name)
                continue
            dim = info.shape[axis]
            if dim < self._axis_size:
                # splitting fewer rows than devices yields mostly-padding
                # shards that are all-gathered every step for no benefit
                logging.warning("var %s dim %d < %d mesh devices; keeping "
                                "replicated", node.var_name, dim, self._axis_size)
                layouts[node.var_name] = VarLayout(name=node.var_name)
                continue
            padded = -(-dim // self._axis_size) * self._axis_size  # ceil to multiple
            layouts[node.var_name] = VarLayout(
                name=node.var_name, partitioned=True, axis=axis,
                num_shards=node.num_shards, orig_dim=dim, padded_dim=padded,
                mesh_axis=self._mesh_axis,
                shard_sizes=tuple(node.shard_sizes) if node.shard_sizes else None)
        # vars without a node config default to replicated
        for name in self._var_infos:
            layouts.setdefault(name, VarLayout(name=name))
        n_part = sum(1 for l in layouts.values() if l.partitioned)
        logging.debug("VariablePartitioner: %d/%d vars partitioned", n_part, len(layouts))
        return layouts
