"""Jaxpr traversal toolbox.

Analog of reference ``autodist/kernel/common/utils.py`` — the graph-surgery
helpers (consumer queries ``:102-129``, BFS ``traverse``/``get_ancestors``
``:132-187``, input rewiring ``:190-259``). Jaxprs are immutable, so there is
no in-place rewiring; what transfers is the *query* half: producers,
consumers, ancestor sets, and primitive search, recursing through
control-flow sub-jaxprs. These power sparse detection today and future
strategy passes (e.g. locating attention blocks for sequence parallelism).
"""
from collections import deque
from typing import Callable, Dict, List, Set

from autodist_tpu.kernel.common import op_info


def _atom_vars(atoms):
    return [a for a in atoms if not hasattr(a, "val")]  # drop Literals


def producers(jaxpr) -> Dict[object, object]:
    """Map each var to the eqn that produces it (None for invars)."""
    out = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            out[v] = eqn
    return out


def consumers(jaxpr, var) -> List[object]:
    """Eqns that read ``var`` (reference ``get_consumers``, ``:102-115``)."""
    return [eqn for eqn in jaxpr.eqns if var in _atom_vars(eqn.invars)]


def get_ancestors(jaxpr, var) -> Set[object]:
    """All vars reachable backwards from ``var``
    (reference ``get_ancestors``, ``:150-187``)."""
    prod = producers(jaxpr)
    seen: Set[object] = set()
    queue = deque([var])
    while queue:
        v = queue.popleft()
        if v in seen:
            continue
        seen.add(v)
        eqn = prod.get(v)
        if eqn is not None:
            queue.extend(_atom_vars(eqn.invars))
    return seen


def traverse(jaxpr, visit: Callable[[object], None], recursive: bool = True):
    """BFS over eqns, optionally descending into control-flow sub-jaxprs
    (reference ``traverse``, ``:132-148``)."""
    queue = deque([jaxpr])
    while queue:
        jp = queue.popleft()
        for eqn in jp.eqns:
            visit(eqn)
            if recursive:
                queue.extend(op_info.sub_jaxprs(eqn))


def find_primitives(jaxpr, names, recursive: bool = True) -> List[object]:
    """All eqns whose primitive name is in ``names``."""
    names = frozenset(names)
    hits: List[object] = []
    traverse(jaxpr, lambda eqn: hits.append(eqn)
             if eqn.primitive.name in names else None, recursive)
    return hits


def uses_control_flow(jaxpr) -> bool:
    """True when while/cond/scan appears anywhere, descending through
    container primitives (inner jits) which are not themselves control flow."""
    return bool(find_primitives(jaxpr, op_info.CONTROL_FLOW_PRIMITIVES,
                                recursive=True))


def count_flops_estimate(jaxpr) -> int:
    """Rough dot/conv FLOP count — used by the simulator's cost model."""
    import numpy as np
    total = 0

    def visit(eqn):
        nonlocal total
        if eqn.primitive.name in ("dot_general", "conv_general_dilated"):
            out = eqn.outvars[0].aval
            lhs = eqn.invars[0].aval
            # 2 * output elements * contraction length (approximate)
            k = int(np.prod(lhs.shape)) // max(
                int(np.prod(out.shape[:1] or (1,))), 1)
            total += 2 * int(np.prod(out.shape)) * max(k, 1)
    traverse(jaxpr, visit)
    return total

# jaxpr primitives that move bytes across mesh axes: (axis param key,
# cost class). Classes matter because the profile is taken from a trace
# with every axis bound at SIZE 1, so each primitive's traced output
# relates differently to its real per-device wire at axis size k:
#   reduce  (psum/pmax/pmin): traced out == full payload at any k;
#           ring wire ~ 2(k-1)/k x traced bytes.
#   gather  (all_gather): traced out == the per-device SHARD at size 1;
#           real wire ~ (k-1) x traced bytes.
#   scatter (reduce_scatter): traced out == the FULL input at size 1;
#           real wire ~ (k-1)/k x traced bytes.
#   alltoall: traced buffer size is k-invariant (split/concat cancel);
#           real wire ~ (k-1)/k x traced bytes.
#   permute (ring rotations): size-1 traces see one full-size block
#           where the real program does ~k rotations of 1/k blocks;
#           total wire ~ (k-1)/k x traced bytes.
_COLLECTIVE_KINDS = {
    "psum": ("axes", "reduce"), "pmax": ("axes", "reduce"),
    "pmin": ("axes", "reduce"),
    "all_gather": ("axis_name", "gather"),
    "reduce_scatter": ("axis_name", "scatter"),
    "all_to_all": ("axis_name", "alltoall"),
    "ppermute": ("axis_name", "permute"),
}


def collective_comm_profile(jaxpr, while_trip_count: int = 1) -> dict:
    """{mesh axis name: {cost class: payload bytes}} for the collectives
    a traced program issues — the cost-model input for MODEL-PARALLEL
    communication (Megatron psums, ring-attention ppermutes, MoE
    all_to_alls), which the per-variable strategy terms cannot see
    because these collectives live inside the user's forward. Bytes are
    the collective OUTPUT avals at trace shapes; scan bodies multiply by
    trip count (a scanned L-layer stack issues L psums, not one).

    Known limits: ``while_loop`` trip counts are statically unknowable,
    so collectives inside a while body are counted ``while_trip_count``
    times (default 1 — an UNDERCOUNT for iterative programs such as
    decoding loops; pass an expected iteration count to make the
    assumption explicit). ``cond`` branches are all summed, as if every
    branch ran — an overcount bounded by the number of branches."""
    import numpy as np
    from autodist_tpu.kernel.common import op_info
    profile: dict = {}

    def walk(jp, mult):
        for eqn in jp.eqns:
            name = eqn.primitive.name
            # materialize: sub_jaxprs is a generator, and a generator is
            # truthy even when it yields nothing
            subs = list(op_info.sub_jaxprs(eqn))
            if name == "scan":
                inner = mult * int(eqn.params.get("length", 1) or 1)
                for sub in subs:
                    walk(sub, inner)
                continue
            if name == "while":
                for sub in subs:
                    walk(sub, mult * max(int(while_trip_count), 1))
                continue
            if subs:
                for sub in subs:
                    walk(sub, mult)
                continue
            key_kind = _COLLECTIVE_KINDS.get(name)
            if key_kind is None:
                continue
            key, kind = key_kind
            axes = eqn.params.get(key, ())
            if isinstance(axes, str):
                axes = (axes,)
            nbytes = mult * sum(
                int(np.prod(ov.aval.shape or (1,)))
                * np.dtype(ov.aval.dtype).itemsize
                for ov in eqn.outvars if hasattr(ov.aval, "shape"))
            for axis in axes:
                by_kind = profile.setdefault(axis, {})
                by_kind[kind] = by_kind.get(kind, 0.0) + float(nbytes)
    walk(jaxpr, 1)
    return profile
