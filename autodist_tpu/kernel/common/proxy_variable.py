"""ProxyVariable — worker-local parameter caching.

Analog of reference ``autodist/kernel/common/proxy_variable.py:74-191``: a
nontrainable clone of a PS-hosted variable on the worker device, with reads
rewired to the clone and refresh ops after each gradient application. Under
SPMD the "proxy" question becomes *where a parameter rests between steps*:

- ``cached=True`` (the reference's proxy): the variable rests replicated on
  every device; no per-step parameter traffic — only gradient collectives.
  This is the lowering's default for unpartitioned vars, so a proxy config
  is the natural state on TPU (the reference had to build it by hand).
- ``cached=False`` (no proxy — PS-resident): the variable rests sharded on
  its owner (ZeRO-style, the partitioned layout) and is all-gathered at the
  start of each step — per-step parameter traffic in exchange for 1/N
  resident memory, exactly the reference's no-proxy read-from-PS cost.

``ProxyVariable.plan`` makes that decision explicit per variable, so PS
configs with ``local_replication`` toggle between the two layouts.
"""
import dataclasses

from autodist_tpu.kernel.partitioner import VarLayout


@dataclasses.dataclass
class ProxyPlan:
    var_name: str
    cached: bool          # True: replicated-at-rest; False: sharded-at-rest
    refresh_every_step: bool = True  # proxies refresh after each apply


class ProxyVariable:
    @staticmethod
    def plan(var_name: str, ps_config, layout: VarLayout) -> ProxyPlan:
        """Decide the at-rest placement for a PS-synchronized variable."""
        if layout.partitioned:
            # sharded storage IS the PS-resident form; a proxy would defeat
            # the memory sharding, so local_replication is ignored here
            return ProxyPlan(var_name, cached=False)
        # Unpartitioned PS vars currently always rest replicated (the proxy
        # form); a true owner-resident unpartitioned variable awaits the
        # host-offload PS path (parallel/ps.py).
        return ProxyPlan(var_name, cached=True)
