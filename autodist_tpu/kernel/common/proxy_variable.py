"""ProxyVariable — worker-local parameter caching.

Analog of reference ``autodist/kernel/common/proxy_variable.py:74-191``: a
nontrainable clone of a PS-hosted variable on the worker device, with reads
rewired to the clone and refresh ops after each gradient application. Under
SPMD the "proxy" question becomes *where a parameter rests between steps*,
and the answer changes the compiled program (``parallel/ps.py``):

- ``cached=True`` (the reference's proxy, ``local_replication=True``): the
  variable rests on device — replicated for unpartitioned vars (updated in
  place by the on-device optimizer; only gradient collectives cross the
  wire), ZeRO-sharded for partitioned vars. This is the reference's
  worker-local clone: reads are free, and the "refresh op after apply" is
  the on-device update itself.
- ``cached=False`` (no proxy — PS-resident, the reference's default): the
  variable and its optimizer state rest in HOST memory
  (``parallel/ps.py:PSStore``); every step pulls the value host->device and
  pushes the reduced gradient device->host, where the update applies on the
  host CPU — exactly the reference's read-from-PS + update-on-PS data path
  (reference ``ps_synchronizer.py:171-176``), with PCIe/DCN standing in for
  gRPC.
"""
import dataclasses

from autodist_tpu.kernel.partitioner import VarLayout


@dataclasses.dataclass
class ProxyPlan:
    var_name: str
    cached: bool          # True: device-resident; False: host-PS-resident
    refresh_every_step: bool = True  # proxies refresh after each apply


class ProxyVariable:
    @staticmethod
    def plan(var_name: str, ps_config, layout: VarLayout) -> ProxyPlan:
        """Decide the at-rest placement for a PS-synchronized variable:
        ``local_replication`` toggles device-cached vs host-resident."""
        return ProxyPlan(var_name,
                         cached=bool(getattr(ps_config, "local_replication",
                                             False)))
