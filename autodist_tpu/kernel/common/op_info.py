"""Primitive-recognition tables.

Analog of reference ``autodist/kernel/common/op_info.py`` — the tables of TF
op types by which AutoDist *recognizes* structure generically (dense/sparse
update ops ``:24-117``, queue/iterator ops ``:119-149``, mutable-state ops
``:151-163``, control-flow ops ``:165-181``). In JAX the graph is a jaxpr
and the same recognition works on primitive names: these tables drive
sparse-variable detection (``model_item.detect_sparse_vars``) and the
jaxpr-traversal utilities (``kernel/common/utils.py``).
"""

# Shape-preserving primitives through which variable identity is tracked
# when looking for indexed reads (the reference's notion of a variable's
# read op chain, ``common/variable_utils.py``).
TRANSPARENT_PRIMITIVES = frozenset({
    "reshape", "transpose", "convert_element_type", "squeeze",
    "broadcast_in_dim", "copy", "stop_gradient", "slice", "rev",
})

# Primitives that perform an indexed (row-wise) read of their first operand
# — the recognition behind "this variable has sparse gradients" (the
# reference checks for IndexedSlices / sparse update op types, ``:73-117``).
INDEXED_READ_PRIMITIVES = frozenset({"gather"})

# Primitives that perform an indexed write (scatter family) — the analog of
# the sparse update-op table (``:73-117``). JAX names these with hyphens
# (lax.scatter_mul_p.name == 'scatter-mul').
INDEXED_UPDATE_PRIMITIVES = frozenset({
    "scatter", "scatter-add", "scatter-mul", "scatter-min", "scatter-max",
})

# Cross-replica collectives (the analog of CollectiveReduce/Gather types).
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "pgather", "axis_index",
})

# Structured-control-flow primitives (the analog of the while/cond op table,
# ``:165-181``).
CONTROL_FLOW_PRIMITIVES = frozenset({"while", "cond", "scan"})

# Container primitives that merely wrap a sub-jaxpr (inner jits from jnp ops,
# custom-derivative wrappers, remat) — traversal descends through these but
# they are not themselves control flow. jax 0.9 names inner jits 'jit'.
CONTAINER_PRIMITIVES = frozenset({
    "jit", "pjit", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat", "checkpoint",
})

# Primitives whose execution has side effects / ordering constraints (the
# analog of the mutable-state & queue op tables, ``:119-163``).
EFFECTFUL_PRIMITIVES = frozenset({
    "io_callback", "pure_callback", "debug_callback", "infeed", "outfeed",
})


def sub_jaxprs(eqn):
    """Yield the sub-jaxprs carried in an eqn's params (cond/scan/pjit
    carry ClosedJaxprs; shard_map carries a raw Jaxpr)."""
    for val in eqn.params.values():
        for item in (val if isinstance(val, (list, tuple)) else (val,)):
            if hasattr(item, "jaxpr"):
                yield item.jaxpr
            elif hasattr(item, "eqns"):  # a raw Jaxpr
                yield item
