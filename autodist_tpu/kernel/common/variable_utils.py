"""Pytree/variable naming utilities.

Analog of reference ``autodist/kernel/common/variable_utils.py`` and parts of
``common/utils.py:24-99`` (name parsing). The reference's problem — finding
read/update ops for Ref vs Resource variables — doesn't exist in JAX; the
equivalent bookkeeping is deterministic flattening of params/optimizer-state
pytrees to named leaves and matching optimizer-state leaves to the variable
they track.
"""
from typing import Any, Dict, List, Tuple

import jax
from jax.tree_util import tree_flatten_with_path, tree_unflatten

from autodist_tpu.model_item import _normalize_path


def flatten_named(tree) -> Tuple[List[str], List[Any], Any]:
    """Flatten to (names, leaves, treedef) in deterministic path order."""
    flat, treedef = tree_flatten_with_path(tree)
    names = [_normalize_path(path) for path, _ in flat]
    leaves = [leaf for _, leaf in flat]
    return names, leaves, treedef


def unflatten_named(treedef, leaves):
    return tree_unflatten(treedef, leaves)


def match_state_to_var(state_name: str, state_shape, var_infos,
                       var_layouts: Dict[str, Any] = None) -> str:
    """Map an optimizer-state leaf to the variable it tracks.

    A state leaf (e.g. ``0/mu/dense/kernel`` for adam's first moment of
    ``dense/kernel``) matches a variable when the variable's name is a
    path-suffix of the state leaf's name and the shapes agree — either the
    variable's original shape, or (when ``var_layouts`` is given) its
    partition-padded shape, so state already placed on the mesh still
    matches. Returns the variable name or '' when the leaf is
    variable-independent (step counts, scalars). This replaces the
    reference's deletion/rebuild of entire optimizer name scopes
    (``kernel/partitioner.py:376-426``)."""
    best = ""
    for var_name, info in var_infos.items():
        shapes = [tuple(info.shape)]
        lay = (var_layouts or {}).get(var_name)
        if lay is not None and getattr(lay, "partitioned", False):
            padded = list(info.shape)
            padded[lay.axis] = lay.padded_dim
            shapes.append(tuple(padded))
        if tuple(state_shape) not in shapes:
            continue
        if state_name == var_name or state_name.endswith("/" + var_name):
            if len(var_name) > len(best):
                best = var_name
    return best


def map_state_layouts(state_tree, var_infos, var_layouts: Dict[str, Any], default):
    """Build a pytree (same structure as ``state_tree``) whose leaves are the
    layout of the matched variable, or ``default`` for unmatched leaves."""
    flat, treedef = tree_flatten_with_path(state_tree)
    out = []
    for path, leaf in flat:
        name = _normalize_path(path)
        shape = getattr(leaf, "shape", ())
        var = match_state_to_var(name, shape, var_infos, var_layouts)
        out.append(var_layouts.get(var, default) if var else default)
    return tree_unflatten(treedef, out)


def is_scalar_leaf(leaf) -> bool:
    return getattr(leaf, "shape", ()) == ()


def tree_zeros_like(tree):
    return jax.tree_util.tree_map(lambda x: jax.numpy.zeros_like(x), tree)


def zero_cotangent(x):
    """Zero cotangent for a possibly-integer operand — float0 for
    non-inexact dtypes (the tangent type JAX assigns non-differentiable
    inputs in custom_vjp backward rules)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    if x is None:
        return None
    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        return jnp.zeros_like(x)
    return np.zeros(jnp.shape(x), jax.dtypes.float0)
