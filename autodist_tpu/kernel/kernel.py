"""Kernel abstract base.

Analog of reference ``autodist/kernel/kernel.py:19-35``: a graph-transforming
kernel exposes a classmethod ``apply`` and keeps its constructor private.
Here kernels don't mutate a graph — they contribute pieces of the lowered
SPMD step function (layouts, gradient-sync transforms) — but the pipeline
shape (Partitioner -> Replicator -> Synchronizers, orchestrated by the
GraphTransformer) is preserved.
"""
from abc import ABC, abstractmethod


class Kernel(ABC):
    _key = object()

    def __init__(self, key, *args, **kwargs):
        if key is not self._key:
            raise ValueError("Kernels must be constructed via .apply()")

    @classmethod
    def apply(cls, *args, **kwargs):
        return cls(cls._key, *args, **kwargs)._apply()

    @abstractmethod
    def _apply(self):
        ...
