"""Device-name resolution.

Analog of reference ``autodist/kernel/device/resolver.py:25-67``, which maps
AutoDist ``ip:GPU:0`` strings to TF ``/job:worker/task:i/device:GPU:0``
strings via the cluster spec. Here the execution substrate is a JAX device
mesh, so the canonical form is the normalized ``host:TYPE:index`` string plus
a deterministic *global ordinal* — the index of that device in the
deterministic device ordering used to build the mesh
(``parallel/mesh.py``). Determinism across independently-launched processes
is what makes every worker lower the same strategy identically (the
reference leans on sorted ip:port ordering the same way,
``cluster.py:73-82``).
"""
from typing import List

from autodist_tpu.resource_spec import DeviceSpec, ResourceSpec


class DeviceResolver:
    def __init__(self, resource_spec: ResourceSpec):
        self._spec = resource_spec
        self._ordered: List[str] = [d.name_string() for d in resource_spec.devices]
        self._index = {name: i for i, name in enumerate(self._ordered)}

    def resolve(self, name: str) -> str:
        """Normalize a device string and validate it exists in the cluster."""
        canonical = DeviceSpec.from_string(name).name_string()
        if canonical not in self._index:
            # CPU host devices are allowed as PS destinations even when the
            # compute devices are TPUs (host-offloaded parameters).
            cpu_names = {d.name_string() for d in self._spec.cpu_devices}
            if canonical in cpu_names:
                return canonical
            raise ValueError("unknown device %r (cluster has %s)" % (name, self._ordered))
        return canonical

    def resolve_many(self, names) -> List[str]:
        return [self.resolve(n) for n in names]

    def global_ordinal(self, name: str) -> int:
        """Deterministic position of this device in the mesh device order."""
        canonical = DeviceSpec.from_string(name).name_string()
        if canonical in self._index:
            return self._index[canonical]
        # host CPU destinations map to the ordinal of the first compute
        # device on the same host (its owning process)
        host = DeviceSpec.from_string(name).host
        for i, dev in enumerate(self._ordered):
            if dev.split(":")[0] == host:
                return i
        raise ValueError("no device on host %s" % host)

    @property
    def ordered_devices(self) -> List[str]:
        return list(self._ordered)
