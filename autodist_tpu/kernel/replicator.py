"""Replicator — data-parallel replication bookkeeping.

Analog of reference ``autodist/kernel/replicator.py:60-156``, which
re-imports the GraphDef once per local device under ``AutoDist-Replica-i/``
name scopes and rewires savers/variables/feeds per replica. Under SPMD
there is nothing to copy: the mesh's batch axes *are* the replica set —
one traced program runs on every device with the batch sharded along those
axes, and XLA's SPMD partitioner performs the replication the reference
did with ``import_graph_def`` x N. What remains — and what this kernel
owns for the GraphTransformer — is the replication bookkeeping: the
replica count, the per-leaf batch PartitionSpec (including the sequence
axis for SP losses), and the batch/sequence division factors used to
derive per-device local shapes (in-graph replication ≡ local mesh
devices; between-graph replication ≡ the same axes spanning processes —
reference ``docs/design/architecture.rst:43-47``).
"""
from typing import Optional, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.kernel.kernel import Kernel


class Replicator(Kernel):
    def __init__(self, key, mesh, batch_axes: Tuple[str, ...],
                 seq_axis: Optional[str] = None, seq_keys=None):
        super().__init__(key)
        self._mesh = mesh
        self._batch_axes = tuple(batch_axes)
        self._seq_axis = seq_axis
        self._seq_keys = seq_keys

    def _apply(self) -> "ReplicaInfo":
        return ReplicaInfo(self._mesh, self._batch_axes, self._seq_axis,
                           self._seq_keys)


class ReplicaInfo:
    """The lowering's single source for replica facts (consumed by
    ``GraphTransformer.transform``)."""

    def __init__(self, mesh, batch_axes: Tuple[str, ...],
                 seq_axis: Optional[str] = None, seq_keys=None):
        self.mesh = mesh
        self.batch_axes = tuple(batch_axes)
        self.seq_axis = seq_axis
        # leaf names whose dim 1 is the sequence dim; None = every
        # rank>=2 leaf (strategy graph_config.seq_feed_keys)
        self.seq_keys = frozenset(seq_keys) if seq_keys else None

    @property
    def num_replicas(self) -> int:
        """Replicas = total batch-axis extent (the reference's replica
        count was its device-list length)."""
        return int(np.prod([self.mesh.shape[a] for a in self.batch_axes]))

    @property
    def batch_factor(self) -> int:
        """Leading-dim division factor from host-global to per-device."""
        return self.num_replicas

    @property
    def seq_factor(self) -> int:
        """Sequence-dim division factor (1 without sequence parallelism)."""
        return int(self.mesh.shape[self.seq_axis]) if self.seq_axis else 1

    def _seq_applies(self, ndim: int, name: Optional[str]) -> bool:
        """Whether dim 1 of this leaf shards over the sequence axis.
        With ``seq_keys`` declared, only the named leaves do — a one-hot
        label leaf [B, C] must not have its CLASS dim sliced; without the
        declaration every rank>=2 leaf does (legacy), which is only
        correct when the batch is all token-shaped arrays."""
        if not self.seq_axis or ndim < 2:
            return False
        return self.seq_keys is None or name in self.seq_keys

    def batch_spec(self, ndim: int, name: Optional[str] = None) -> P:
        """PartitionSpec for one batch leaf: leading dim over the batch
        axes; dim 1 over the sequence axis when ``_seq_applies``."""
        if ndim == 0:
            return P()
        if self._seq_applies(ndim, name):
            return P(self.batch_axes, self.seq_axis)
        return P(self.batch_axes)

    def local_shape(self, shape: Tuple[int, ...],
                    name: Optional[str] = None) -> Tuple[int, ...]:
        """Per-device shape of a batch leaf, when divisible — the inverse
        of the sharding ``batch_spec`` declares."""
        shape = list(shape)
        if len(shape) >= 1 and shape[0] % self.batch_factor == 0:
            shape[0] //= self.batch_factor
        if self._seq_applies(len(shape), name) \
                and shape[1] % self.seq_factor == 0:
            shape[1] //= self.seq_factor
        return tuple(shape)

    def replica_name(self, i: int) -> str:
        return const.REPLICA_PREFIX.format(i)
