"""Replicator — data-parallel replication bookkeeping.

Analog of reference ``autodist/kernel/replicator.py:60-156``, which
re-imports the GraphDef once per local device under ``AutoDist-Replica-i/``
name scopes and rewires savers/variables/feeds per replica. Under SPMD
there is nothing to copy: the mesh's batch axes *are* the replica set —
one traced program runs on every device with the batch sharded along those
axes, and XLA's SPMD partitioner performs the replication the reference
did with ``import_graph_def`` x N. What remains — and what this kernel
owns for the GraphTransformer — is the replication bookkeeping: the
replica count, the per-leaf batch PartitionSpec (including the sequence
axis for SP losses), and the batch/sequence division factors used to
derive per-device local shapes (in-graph replication ≡ local mesh
devices; between-graph replication ≡ the same axes spanning processes —
reference ``docs/design/architecture.rst:43-47``).
"""
from typing import Optional, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.kernel.kernel import Kernel


class Replicator(Kernel):
    def __init__(self, key, mesh, batch_axes: Tuple[str, ...],
                 seq_axis: Optional[str] = None):
        super().__init__(key)
        self._mesh = mesh
        self._batch_axes = tuple(batch_axes)
        self._seq_axis = seq_axis

    def _apply(self) -> "ReplicaInfo":
        return ReplicaInfo(self._mesh, self._batch_axes, self._seq_axis)


class ReplicaInfo:
    """The lowering's single source for replica facts (consumed by
    ``GraphTransformer.transform``)."""

    def __init__(self, mesh, batch_axes: Tuple[str, ...],
                 seq_axis: Optional[str] = None):
        self.mesh = mesh
        self.batch_axes = tuple(batch_axes)
        self.seq_axis = seq_axis

    @property
    def num_replicas(self) -> int:
        """Replicas = total batch-axis extent (the reference's replica
        count was its device-list length)."""
        return int(np.prod([self.mesh.shape[a] for a in self.batch_axes]))

    @property
    def batch_factor(self) -> int:
        """Leading-dim division factor from host-global to per-device."""
        return self.num_replicas

    @property
    def seq_factor(self) -> int:
        """Sequence-dim division factor (1 without sequence parallelism)."""
        return int(self.mesh.shape[self.seq_axis]) if self.seq_axis else 1

    def batch_spec(self, ndim: int) -> P:
        """PartitionSpec for one batch leaf: leading dim over the batch
        axes; dim 1 over the sequence axis for rank>=2 leaves under SP."""
        if ndim == 0:
            return P()
        if self.seq_axis and ndim >= 2:
            return P(self.batch_axes, self.seq_axis)
        return P(self.batch_axes)

    def local_shape(self, shape: Tuple[int, ...]) -> Tuple[int, ...]:
        """Per-device shape of a batch leaf, when divisible — the inverse
        of the sharding ``batch_spec`` declares."""
        shape = list(shape)
        if len(shape) >= 1 and shape[0] % self.batch_factor == 0:
            shape[0] //= self.batch_factor
        if self.seq_factor > 1 and len(shape) >= 2 \
                and shape[1] % self.seq_factor == 0:
            shape[1] //= self.seq_factor
        return tuple(shape)

    def replica_name(self, i: int) -> str:
        return const.REPLICA_PREFIX.format(i)
