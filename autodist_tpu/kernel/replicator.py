"""Replicator — data-parallel replication.

Analog of reference ``autodist/kernel/replicator.py:60-156``, which re-imports
the GraphDef once per local device under ``AutoDist-Replica-i/`` name scopes
and rewires savers/variables/feeds per replica. Under SPMD there is nothing
to copy: the mesh's data axis *is* the replica set — one traced program runs
on every device with the batch sharded along that axis, and XLA's SPMD
partitioner performs the replication the reference did with
``import_graph_def`` × N. What remains of the Replicator is the bookkeeping:
replica count/devices and the batch-sharding spec it contributes to the
lowering (in-graph replication ≡ local mesh devices; between-graph
replication ≡ the same axis spanning processes — reference
``docs/design/architecture.rst:43-47``).
"""
from typing import List

from jax.sharding import PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.kernel.kernel import Kernel


class Replicator(Kernel):
    def __init__(self, key, replica_devices: List[str], mesh,
                 mesh_axis: str = const.DATA_AXIS):
        super().__init__(key)
        self._replica_devices = replica_devices
        self._mesh = mesh
        self._axis = mesh_axis

    def _apply(self):
        return ReplicaInfo(self._replica_devices, self._mesh, self._axis)


class ReplicaInfo:
    def __init__(self, replica_devices, mesh, mesh_axis):
        self.replica_devices = list(replica_devices)
        self.mesh = mesh
        self.mesh_axis = mesh_axis

    @property
    def num_replicas(self) -> int:
        return len(self.replica_devices)

    @property
    def batch_spec(self) -> P:
        """Shard the leading (batch) dim across replicas."""
        return P(self.mesh_axis)

    def replica_name(self, i: int) -> str:
        return const.REPLICA_PREFIX.format(i)
