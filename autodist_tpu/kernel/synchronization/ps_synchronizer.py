"""PS synchronizer kernel.

Analog of reference
``autodist/kernel/synchronization/ps_synchronizer.py`` (761 LoC of graph
surgery). The reference's machinery maps onto TPU as follows:

- *In-graph apply* (share replica-0 variable, aggregate local grads on the
  worker CPU, ``ps_synchronizer.py:105-152,460-535``): under SPMD all local
  replicas already share one logical variable; the local aggregation is the
  first hop of the single ``psum``.
- *Between-graph apply* (place var+update on the PS device, per-worker
  accumulators, token-queue barriers, ``:171-176,335-458,556-633``): with
  ``local_replication=False`` (no proxy — the reference's default) the
  variable takes the REAL host-offloaded PS data path in ``parallel/ps.py``:
  values + optimizer state rest in host memory, pulled to device each step,
  gradients pushed back and applied host-side — and this kernel is never
  instantiated. This class handles only the **proxied** case
  (``local_replication=True``, the reference's worker-local cache): the
  variable rests on device, and the synchronous dance — "push grads, owner
  averages over num_workers, applies, workers wait for the token" — is
  exactly the semantics of one mean ``psum`` followed by a (redundantly
  computed, hence communication-free) update: every device leaves the step
  with the identical post-update value, which is what the token queue
  guaranteed.
- *Staleness* (``:388-458``): bounded staleness is a runtime-scheduling
  property on TPU, implemented by the Runner's cross-process pacing
  through the native coordination service
  (``runtime/coordination.py``): each process reports its step and blocks
  while more than ``staleness`` steps ahead of the slowest worker — the
  semantics the reference built from size-``s`` token queues. Fully-async
  PS (``sync=False``) is a host-store property (``parallel/ps.py``); an
  async PROXIED var is contradictory (a device-cached copy updated in
  lockstep cannot be async) and warns.
"""
from autodist_tpu.kernel.synchronization.synchronizer import Synchronizer


class PSSynchronizer(Synchronizer):
    def __init__(self, var_name, config, num_replicas, mesh_axis="data",
                 layout=None, extra_axes=(), dcn_axes=()):
        super().__init__(var_name, config, num_replicas, mesh_axis, layout,
                         extra_axes, dcn_axes)
        self.reduction_destination = getattr(config, "reduction_destination", "")
        self.local_replication = getattr(config, "local_replication", False)
        self.sync_mode = getattr(config, "sync", True)
        self.staleness = getattr(config, "staleness", 0)
        # host<->device wire format of the no-proxy PS path (consumed by
        # plan_host_ps -> PSVarPlan; this kernel only lowers the PROXIED
        # case, where there is no host wire to quantize)
        self.wire_dtype = getattr(config, "wire_dtype", "fp32") or "fp32"
        if self.wire_dtype == "int8" and self.local_replication:
            from autodist_tpu.utils import logging
            logging.warning(
                "var %s: wire_dtype=int8 with local_replication=True is "
                "ignored — a proxied PS var is device-resident and its "
                "sync is an on-device psum, no host wire exists (ADT310)",
                var_name)
        if not self.sync_mode:
            from autodist_tpu.utils import logging
            logging.warning(
                "var %s: sync=False with local_replication=True is "
                "contradictory — a device-cached proxy updates in lockstep; "
                "drop the proxy to get the async host-PS path", var_name)

    def sync(self, grad, state):
        if self.layout is not None and self.layout.partitioned:
            local = self.psum_extra(self.layout.reduce_scatter_grad(grad))
            return local / self.num_replicas, state
        return self.psum(grad) / self.num_replicas, state
