"""AllReduce synchronizer kernel.

Analog of reference
``autodist/kernel/synchronization/all_reduce_synchronizer.py:102-130``: the
reference replaces each replica's gradient with a CollectiveReduce (mean via
merge=Add, final=Div) keyed so all workers agree. Here the collective is
``jax.lax.psum`` over the mesh's data axis — XLA lowers it onto ICI
(intra-slice) or DCN (cross-slice) per the mesh; the ``spec`` hint is kept
as metadata. Compression wraps the collective
(``kernel/synchronization/compressor.py``); partitioned variables take the
reduce-scatter path (each device receives only its shard of the summed
gradient — the ICI-native realization of "partition then all-reduce each
shard", reference ``partitioned_all_reduce_strategy.py:71-117``).

Sparse gradients: the reference all-gathers indices+values
(``all_reduce_synchronizer.py:132-173``). JAX gradients arrive dense; the
sparse fast path lives in ``ops/embedding.py`` (row-gathered updates) and is
routed by the lowering when a variable is marked sparse.
"""
from autodist_tpu.kernel.synchronization import compressor as compressor_lib
from autodist_tpu.kernel.synchronization.synchronizer import Synchronizer
from autodist_tpu.utils import logging


class AllReduceSynchronizer(Synchronizer):
    def __init__(self, var_name, config, num_replicas, mesh_axis="data",
                 layout=None, extra_axes=(), dcn_axes=()):
        super().__init__(var_name, config, num_replicas, mesh_axis, layout,
                         extra_axes, dcn_axes)
        self.compressor = compressor_lib.create(
            getattr(config, "compressor", None), var_name)
        # wire_dtype="int8" lowers the collective itself to the blockwise
        # two-phase quantized all-reduce: implemented by substituting the
        # Int8CompressorEF wire codec (error feedback keeps training
        # honest), which the bucketing layer then arms with the mesh axes.
        # A var that also names an explicit compressor keeps it (the
        # conflict is the linter's ADT310 error).
        self.wire_dtype = getattr(config, "wire_dtype", "fp32") or "fp32"
        if (self.wire_dtype == "int8"
                and self.compressor.name == "NoneCompressor"
                and not (layout is not None and layout.partitioned)):
            self.compressor = compressor_lib.create("Int8CompressorEF",
                                                    var_name)
        # NOTE: int8 wire arming happens in bucket_reduce — every
        # unpartitioned int8 var is concatable and routed into a bucket;
        # this per-var compressor only serves the psum fallback paths
        self.group = getattr(config, "group", 0)
        self.spec = getattr(config, "spec", "AUTO")
        # collective algorithm: auto | ring | rhd | hier (strategy/base.py
        # docs; resolution semantics in analysis/topology.py). Consumed in
        # psum() and by the bucketing layer via graph_transformer.
        self.schedule = (getattr(config, "schedule", "auto")
                         or "auto").lower()
        if (layout is not None and layout.partitioned
                and self.compressor.name != "NoneCompressor"):
            logging.warning("var %s: compressor %s is ignored on the "
                            "partitioned (reduce-scatter) path", var_name,
                            self.compressor.name)
        if (layout is not None and layout.partitioned
                and self.wire_dtype == "int8"):
            logging.warning("var %s: wire_dtype=int8 is ignored on the "
                            "partitioned (reduce-scatter) path (ADT310)",
                            var_name)

    def psum(self, x):
        """The ``spec`` hint and the ``schedule`` knob are consumed here:
        ``DCN`` (or ``schedule=hier`` when the mesh has cross-host axes)
        lowers the reduction to the bandwidth-hierarchical form
        (reduce-scatter over ICI, all-reduce the shard over DCN,
        all-gather over ICI) so the slow cross-host links carry 1/N_ici
        of the payload; ``schedule=rhd`` lowers to the explicit
        reduce-scatter + all-gather composition (recursive
        halving/doubling shape). AUTO/ICI ring takes the single fused
        psum and lets XLA schedule it; ``hier`` on a mesh with no
        cross-host axes falls back to that ring (resolver refusal —
        there is nothing to hierarchize)."""
        axes = (self.mesh_axis,) + self.extra_axes
        dcn = tuple(a for a in axes if a in self.dcn_axes)
        if (self.spec == "DCN" or self.schedule == "hier") and dcn:
            from autodist_tpu.parallel.collectives import hierarchical_psum
            ici = tuple(a for a in axes if a not in self.dcn_axes)
            return hierarchical_psum(x, ici, dcn)
        if self.schedule == "rhd":
            from autodist_tpu.parallel.collectives import rhd_psum
            return rhd_psum(x, axes)
        return super().psum(x)

    def state_init(self, grad_shape, dtype):
        return self.compressor.state_init(grad_shape, dtype)

    def sync(self, grad, state):
        if self.layout is not None and self.layout.partitioned:
            # reduce-scatter over the data axis, plain psum over any extra
            # axes, then normalize to mean over all devices
            local = self.psum_extra(self.layout.reduce_scatter_grad(grad))
            return local / self.num_replicas, state
        reduced, new_state = self.compressor.reduce(grad, state, self.psum)
        return reduced / self.num_replicas, new_state
