"""Gradient compressors for all-reduce.

Analog of reference ``autodist/kernel/synchronization/compressor.py:85-206``,
a strategy-pattern wrapper around the collective: ``NoneCompressor``
(passthrough), ``HorovodCompressor`` (reduced-precision transfer — the
reference casts to fp32; on TPU the payload-halving cast is bf16),
``HorovodCompressorEF`` (reduced precision + error feedback residual), and
``PowerSGDCompressor`` — present but fully commented-out in the reference
(``compressor.py:208-284``); implemented for real here (rank-r power
iteration, arXiv 1905.13727), one of the places this framework goes beyond
the reference.

A compressor transforms the payload *around* the all-reduce:
``compress -> psum -> decompress``. Stateful compressors (error feedback,
PowerSGD's warm-started Q) carry their state in the train state's
``sync_state`` pytree, updated functionally each step.
"""
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


class Compressor:
    """Base: stateless passthrough. ``state_spec(grad)`` returns a pytree of
    zeros-like state carried across steps (None when stateless)."""

    name = "NoneCompressor"

    def __init__(self, var_name: str = ""):
        self.var_name = var_name

    def state_init(self, grad_shape, dtype):
        return None

    def reduce(self, grad: jax.Array, state, psum: Callable) -> Tuple[jax.Array, object]:
        """Return (sum-reduced gradient, new state). ``psum`` is the
        axis-bound sum-reduction supplied by the synchronizer, which
        normalizes to a mean afterwards."""
        return psum(grad), state


class NoneCompressor(Compressor):
    pass


class HorovodCompressor(Compressor):
    """Cast payload to a smaller dtype for the wire, cast back after.

    The reference compresses fp64->fp32 (``compressor.py:169-201``); TPU
    gradients are fp32, so the halving cast is bf16."""

    name = "HorovodCompressor"
    wire_dtype = jnp.bfloat16

    def reduce(self, grad, state, psum):
        orig = grad.dtype
        if grad.dtype in (jnp.float32, jnp.float64):
            reduced = psum(grad.astype(self.wire_dtype)).astype(orig)
        else:
            reduced = psum(grad)
        return reduced, state


class HorovodCompressorEF(Compressor):
    """Reduced-precision all-reduce with error feedback
    (reference ``compressor.py:120-143``): the quantization error from this
    step is added back into the next step's gradient, preserving the sum of
    updates over time."""

    name = "HorovodCompressorEF"
    wire_dtype = jnp.bfloat16

    def state_init(self, grad_shape, dtype):
        return jnp.zeros(grad_shape, dtype)

    def reduce(self, grad, state, psum):
        orig = grad.dtype
        compensated = grad + state
        wire = compensated.astype(self.wire_dtype)
        new_state = compensated - wire.astype(orig)  # local quantization error
        reduced = psum(wire).astype(orig)
        return reduced, new_state


class Int8Compressor(Compressor):
    """Blockwise-scaled int8 wire format via the explicit two-phase
    quantized all-reduce (EQuARX, arXiv 2506.17615): quantize ->
    reduce-scatter the int8 payload (one all_to_all) -> local
    dequant-accumulate in f32 -> quantize -> all-gather — ~4x less wire
    traffic than fp32 (1 + 4/block bytes per element, per-block absmax
    scales, block size ``ADT_WIRE_BLOCK``). XLA cannot accumulate int8
    collectives without overflow, which is why the shape is explicit; the
    synchronizer/bucketing layer arms ``ring_axes`` — one two-phase
    reduce per mesh axis, run sequentially, so multi-axis reductions
    (dp x sp, dp x tp) keep the full wire compression. Unarmed (a
    degenerate 1-device reduction), the payload falls back to bf16 psum."""

    name = "Int8Compressor"
    wire_dtype = jnp.bfloat16  # fallback wire when the quantized AR is unarmed

    def __init__(self, var_name: str = ""):
        super().__init__(var_name)
        self.ring_axes = ()     # ((axis_name, size), ...) armed by the lowering

    def _wire_reduce(self, grad):
        from autodist_tpu.parallel import collectives
        flat = grad.reshape(-1).astype(jnp.float32)
        out = collectives.int8_multi_axis_all_reduce(flat, self.ring_axes)
        return out.reshape(grad.shape).astype(grad.dtype)

    # legacy spelling (pre-blockwise callers armed "_ring")
    _ring = _wire_reduce

    def reduce(self, grad, state, psum):
        if not self.ring_axes:
            return HorovodCompressor.reduce(self, grad, state, psum)
        return self._wire_reduce(grad), state


class Int8CompressorEF(Int8Compressor):
    """Blockwise int8 two-phase all-reduce with error feedback: the local
    quantization residual (what the first phase's wire could not
    represent of this replica's compensated gradient) is carried to the
    next step, preserving the sum of updates. The compensated gradient
    goes to the collective DIRECTLY — quantization happens inside the
    two-phase reduce; the residual is computed against the blockwise
    quantized image of the compensated gradient (the first phase's wire
    error) without a second quantize/dequantize round-trip on the
    payload. Unarmed, this is exactly BF16CompressorEF."""

    name = "Int8CompressorEF"

    def state_init(self, grad_shape, dtype):
        return jnp.zeros(grad_shape, dtype)

    def reduce(self, grad, state, psum):
        if not self.ring_axes:
            return HorovodCompressorEF.reduce(self, grad, state, psum)
        compensated = grad + state
        from autodist_tpu.parallel.collectives import (dequant_i8_block,
                                                       quant_i8_block)
        flat = compensated.reshape(-1).astype(jnp.float32)
        q, s = quant_i8_block(flat)
        wire_image = dequant_i8_block(q, s, flat.shape[0]).reshape(
            grad.shape).astype(grad.dtype)
        new_state = compensated - wire_image
        return self._wire_reduce(compensated), new_state


class PowerSGDCompressor(Compressor):
    """Rank-r PowerSGD (arXiv 1905.13727) with error feedback and a
    warm-started Q factor. Communicates P (n x r) + Q (m x r) instead of the
    full n x m gradient. Matrices only; lower-rank tensors pass through.

    The reference carries this as dead commented-out code
    (``compressor.py:208-284``); here it is live and tested."""

    name = "PowerSGDCompressor"

    def __init__(self, var_name: str = "", rank: int = 1):
        super().__init__(var_name)
        self.rank = rank

    def _matrix_shape(self, shape):
        if len(shape) < 2:
            return None
        n = shape[0]
        m = 1
        for d in shape[1:]:
            m *= d
        return n, m

    def state_init(self, grad_shape, dtype):
        nm = self._matrix_shape(grad_shape)
        if nm is None:
            return None
        n, m = nm
        # md5-derived seed: every process must build the identical Q
        # (builtin hash() is randomized per process — see collective_key.py)
        from autodist_tpu.kernel.synchronization.collective_key import CollectiveKey
        key = jax.random.PRNGKey(CollectiveKey.instance_key(self.var_name))
        q = jax.random.normal(key, (m, self.rank), dtype)
        return {"error": jnp.zeros(grad_shape, dtype), "q": q}

    def reduce(self, grad, state, psum):
        nm = self._matrix_shape(grad.shape)
        if nm is None or state is None:
            return psum(grad), state
        n, m = nm
        mat = (grad + state["error"]).reshape(n, m)
        q = state["q"]
        # power iteration: P = M Q (all-reduced), orthonormalize, Q = M^T P
        p = psum(mat @ q)
        p, _ = jnp.linalg.qr(p)
        q_new = psum(mat.T @ p)
        approx = (p @ q_new.T).reshape(grad.shape)
        # the all-reduced approx is a sum over workers already; error is local
        new_error = (grad + state["error"]) - (p @ (mat.T @ p).T).reshape(grad.shape)
        return approx, {"error": new_error, "q": q_new}


_REGISTRY: Dict[str, type] = {
    c.name: c for c in
    (NoneCompressor, HorovodCompressor, HorovodCompressorEF,
     Int8Compressor, Int8CompressorEF, PowerSGDCompressor)
}
# TPU-flavored aliases
_REGISTRY["BF16Compressor"] = HorovodCompressor
_REGISTRY["BF16CompressorEF"] = HorovodCompressorEF


def parse_name(name: str) -> "tuple[str, Optional[int]]":
    """Split a serializable compressor name into (base, rank).

    The one place that knows the ``"PowerSGDCompressor:4"`` wire format;
    rank is None when the name carries no argument. Raises ValueError for a
    dangling ``:``, a non-integer rank, a rank < 1, or an argument on a
    compressor that takes none.
    """
    base, sep, arg = name.partition(":")
    if not sep:
        return base, None
    if base not in _REGISTRY:
        raise ValueError("unknown compressor %r (have %s)" % (name, sorted(_REGISTRY)))
    if _REGISTRY[base] is not PowerSGDCompressor:
        raise ValueError("compressor %r takes no argument" % name)
    try:
        rank = int(arg)
    except ValueError:
        raise ValueError("compressor %r: rank must be an integer" % name)
    if rank < 1:
        raise ValueError("compressor %r: rank must be >= 1" % name)
    return base, rank


def known_names() -> "tuple[str, ...]":
    """Every serializable compressor name (aliases included)."""
    return tuple(sorted(_REGISTRY))


def validate_name(name: str) -> "tuple[str, Optional[int]]":
    """Full validation of a serializable compressor name: format (via
    :func:`parse_name`) AND registry membership. The single check behind
    both the factory below and the linter's ADT305 rule
    (``analysis/rules.py``) — compile time and lint time cannot drift."""
    base, rank = parse_name(name)
    if base not in _REGISTRY:
        raise ValueError("unknown compressor %r (have %s)"
                         % (name, sorted(_REGISTRY)))
    return base, rank


def create(name: Optional[str], var_name: str = "") -> Compressor:
    """Factory by class name (reference ``Compressor.create``). PowerSGD's
    rank rides in the serializable name: ``"PowerSGDCompressor:4"``."""
    if not name:
        return NoneCompressor(var_name)
    base, rank = validate_name(name)
    cls = _REGISTRY[base]
    if rank is not None:
        return cls(var_name, rank=rank)
    return cls(var_name)
