"""ZeRO-sharded weight-update kernel (arXiv 2004.13336, stage 1).

Where the other synchronizer kernels contribute a gradient transform
(``sync(grad, state) -> synced``), the sharded weight update owns the
whole update path of its variable, so the lowering
(``kernel/graph_transformer.py``) drives it through three in-graph
phases instead:

1. :meth:`reduce_scatter` — the full gradient flattens, pads to
   ``n_data`` uniform flat shards, reduce-scatters over the data axis
   (so each replica receives exactly the summed gradient of the shard it
   owns), plain-psums over any extra mesh axes, and mean-normalizes.
2. the lowering applies the optimizer to the owned shard only, against
   the variable's per-replica optimizer-state shard (created sharded in
   ``sync_state['zero']`` — never materialized whole) and the matching
   :meth:`local_shard` slice of the replicated full param.
3. :meth:`gather_update` — the shard's UPDATE (the optax delta, not the
   param) all-gathers back; every replica applies the identical delta to
   its replicated param copy, which therefore accumulates in full
   precision and stays bit-identical across replicas.

``wire_dtype="int8"`` swaps both crossings for the blockwise-quantized
forms (``collectives.int8_block_reduce_scatter`` /
``int8_block_all_gather``): the shard size rounds up to whole scale
blocks so every shard's scales are self-contained, and gathering the
*delta* (small magnitude, fine scale resolution) rather than the params
keeps the lossy wire off the master weights.

Wire accounting: rs + ag move the same ring bytes as one all-reduce
(2(P-1)/P of the payload per link) — the cost model prices them with the
same factor; the static per-step payload (:meth:`rs_payload_bytes` /
:meth:`ag_payload_bytes`) feeds the ``zero.rs_bytes``/``zero.ag_bytes``
telemetry counters so measured and predicted bytes share one formula.
"""
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.parallel import collectives


def zero_shard_elems(num_elements: int, n_data: int,
                     wire_dtype: str = "fp32") -> int:
    """Per-replica flat shard size: ceil split over the data axis,
    rounded up to whole scale blocks on the int8 wire (so every shard's
    scales are self-contained). The ONE shard-shape formula shared by
    the kernel, the cost model's pricing, and the checkpoint re-layout."""
    n_data = max(int(n_data), 1)
    shard = -(-int(num_elements) // n_data)
    if wire_dtype == "int8":
        block = collectives.wire_block_size()
        shard = -(-shard // block) * block
    return int(shard)


def zero_wire_payload_bytes(num_elements: int, n_data: int,
                            wire_dtype: str = "fp32",
                            itemsize: int = 4) -> float:
    """Bytes ONE rs (or ag) crossing of a ZeRO-sharded variable ships:
    the padded flat payload at full width, or the int8 body + f32 scale
    sidecar over the per-shard-block-rounded padding. Shared by the
    kernel's telemetry accounting and ``CostModel._wire_bytes`` so
    predicted and measured bytes can only agree."""
    padded = zero_shard_elems(num_elements, n_data, wire_dtype) \
        * max(int(n_data), 1)
    if wire_dtype == "int8":
        q, _ = collectives.int8_wire_payload_bytes(padded, itemsize)
        return float(q)
    return float(padded) * 4.0


def relayout_zero_sync_leaf(saved, old_axes, old_shape, data_axis, zs,
                            tmpl_shape, tmpl_dtype):
    """Re-lay one saved ``sync_state['zero']`` leaf (leading-device-axis
    ``[N_old, ...]``) onto a NEW topology's template shape
    ``[N_new, ...]``: concatenate the save-topology per-data-index shard
    rows into the global flat value, re-pad to the new shard size, and
    re-broadcast per new device row. Returns the new host array, or
    ``None`` when the leaf is not re-layoutable (caller resets to fresh
    init). Shared by the sharded checkpoint's cross-topology restore and
    the in-run elastic snapshot adoption — one re-shard math, no drift.

    ``zs`` is the NEW program's :class:`ZeroSynchronizer` for the
    owning variable; ``old_axes``/``old_shape`` describe the SAVE-time
    mesh."""
    saved = np.asarray(saved)
    tmpl_shape = tuple(tmpl_shape)
    rest_old, rest_new = saved.shape[1:], tmpl_shape[1:]
    if rest_old == () and rest_new == ():
        # shared little-leaf (optimizer count): replica-identical
        return np.broadcast_to(saved[0][None],
                               tmpl_shape).astype(tmpl_dtype).copy()
    if len(rest_old) != 1 or len(rest_new) != 1:
        return None
    if data_axis not in old_axes:
        return None
    p = list(old_axes).index(data_axis)
    n_old = max(int(old_shape[p]), 1)
    stride_old = int(np.prod(list(old_shape[p + 1:]) or [1]))
    flat_old = np.concatenate(
        [saved[i * stride_old] for i in range(n_old)])
    flat_new = np.zeros(zs.n_data * zs.shard_elems, saved.dtype)
    m = min(flat_old.shape[0], flat_new.shape[0])
    flat_new[:m] = flat_old[:m]
    blocks = flat_new.reshape(zs.n_data, zs.shard_elems)
    out = np.empty(tmpl_shape, tmpl_dtype)
    for r in range(tmpl_shape[0]):
        out[r] = blocks[(r // zs.leading_stride) % zs.n_data]
    return out


class ZeroSynchronizer:
    """Per-variable sharded-update kernel. Pure shape math is host-side
    (shared by the lowering, the checkpoint re-shard, and the byte
    accounting); the three phase methods trace into the step."""

    def __init__(self, var_name: str, config, shape, dtype,
                 mesh_axis: str, n_data: int, extra_axes: Tuple[str, ...],
                 total_devices: int, leading_stride: int = 1):
        self.var_name = var_name
        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype)
        self.mesh_axis = mesh_axis
        self.n_data = max(int(n_data), 1)
        self.extra_axes = tuple(extra_axes)
        self.total_devices = max(int(total_devices), 1)
        # leading-axis stride of the data axis in the sync_state layout
        # (P(all_axes) row-major over mesh axes): row i*stride holds data
        # index i with every other axis at 0 — the host-side unshard and
        # the cross-topology checkpoint re-shard both index with it
        self.leading_stride = max(int(leading_stride), 1)
        self.wire_dtype = getattr(config, "wire_dtype", "fp32") or "fp32"
        self.num_elements = int(np.prod(self.shape or (1,)))
        self.shard_elems = zero_shard_elems(self.num_elements, self.n_data,
                                            self.wire_dtype)
        self.padded_elems = self.shard_elems * self.n_data

    # ------------------------------------------------------------ phases

    def _pad_flat(self, arr):
        flat = jnp.asarray(arr).astype(jnp.float32).reshape(-1)
        return jnp.pad(flat, (0, self.padded_elems - self.num_elements))

    def reduce_scatter(self, grad_full):
        """Full gradient -> this replica's mean-normalized [shard_elems]
        flat chunk (summed over the data axis via reduce-scatter, over
        any extra axes via plain psum)."""
        flat = self._pad_flat(grad_full)
        if self.n_data > 1:
            if self.wire_dtype == "int8":
                local = collectives.int8_block_reduce_scatter(
                    flat, self.mesh_axis, self.n_data)[:self.shard_elems]
            else:
                local = jax.lax.psum_scatter(
                    flat, self.mesh_axis, scatter_dimension=0, tiled=True)
        else:
            local = flat
        if self.extra_axes:
            local = jax.lax.psum(local, self.extra_axes)
        return local / self.total_devices

    def local_shard(self, param_full):
        """This replica's owned [shard_elems] flat slice of the
        replicated full param (f32 — the little-tree optimizer apply
        mirrors the full-precision master copy)."""
        flat = self._pad_flat(param_full)
        idx = (jax.lax.axis_index(self.mesh_axis) if self.n_data > 1
               else jnp.int32(0))
        return jax.lax.dynamic_slice(
            flat, (idx * self.shard_elems,), (self.shard_elems,))

    def gather_update(self, update_shard):
        """Owned shard's update delta -> the full-shape delta every
        replica applies (all-gathered; int8 wire dequantizes the SAME
        bytes everywhere, so the applied delta is bit-identical)."""
        upd = jnp.asarray(update_shard).astype(jnp.float32)
        if self.n_data > 1:
            if self.wire_dtype == "int8":
                full = collectives.int8_block_all_gather(
                    upd, self.mesh_axis, self.n_data)
            else:
                full = jax.lax.all_gather(upd, self.mesh_axis, axis=0,
                                          tiled=True)
        else:
            full = upd
        return (full[:self.num_elements]
                .reshape(self.shape).astype(self.dtype))

    # -------------------------------------------------- host-side helpers

    def opt_state_init(self, optimizer):
        """The per-replica optimizer-state shard template (a little
        ``{"v": [shard_elems]}`` tree through ``optimizer.init``) —
        host-side numpy leaves, broadcast by the lowering's
        ``sync_state_init`` into the leading-device-axis layout.

        Always f32, whatever the resident param dtype: ``local_shard``
        hands the optimizer an f32 view and the ADT602 numerics rule
        exempts ZeroSharded precisely because the sharded update's state
        and arithmetic keep full precision (arXiv 2004.13336)."""
        init = optimizer.init(
            {"v": jnp.zeros((self.shard_elems,), jnp.float32)})
        return jax.tree_util.tree_map(np.asarray, init)

    def unshard_host(self, leading_arr) -> np.ndarray:
        """One gathered ``[N, ...]`` sync-state leaf -> the full
        variable-shaped value (original-layout checkpoints): shard rows
        concatenate in data-axis order; shared (count-like) leaves take
        row 0."""
        arr = np.asarray(leading_arr)
        if arr.shape[1:] != (self.shard_elems,):
            return arr[0]  # shared little-leaf (optimizer count, ...)
        rows = [arr[i * self.leading_stride] for i in range(self.n_data)]
        flat = np.concatenate(rows)[:self.num_elements]
        return flat.reshape(self.shape)

    # ------------------------------------------------------ byte accounting

    def _wire_payload(self) -> float:
        return zero_wire_payload_bytes(self.num_elements, self.n_data,
                                       self.wire_dtype,
                                       self.dtype.itemsize)

    def rs_payload_bytes(self) -> float:
        """Static per-step reduce-scatter payload bytes (int8 body +
        scale sidecar on the quantized wire) — the zero.rs_bytes counter
        and the cost model share this number."""
        return self._wire_payload() if self.n_data > 1 else 0.0

    def ag_payload_bytes(self) -> float:
        """Static per-step update all-gather payload bytes."""
        return self._wire_payload() if self.n_data > 1 else 0.0
