"""Deterministic collective keys.

Analog of reference ``autodist/kernel/synchronization/collective_key.py:43-70``:
the reference generates group keys sequentially per device-set and instance
keys as md5(var_name) mod INT32 so that all workers, building their graphs
independently, agree on collective identities without communicating.

Under XLA SPMD the compiler assigns channel ids itself, so these keys are
not fed to the runtime; they remain the deterministic *ordering* authority —
gradient buckets are concatenated in instance-key order, which must be
identical on every process for the bytes on the wire to line up.
"""
import hashlib

from autodist_tpu.const import MAX_INT32


class CollectiveKey:
    _instance = None

    def __init__(self, group_leader: str = ""):
        self._group_keys = {}
        self._next_group = 1
        self.group_leader = group_leader

    @classmethod
    def get(cls) -> "CollectiveKey":
        if cls._instance is None:
            cls._instance = CollectiveKey()
        return cls._instance

    @classmethod
    def reset(cls):
        cls._instance = None

    def group_key(self, device_set) -> int:
        """Sequential key per canonical device set."""
        canon = ",".join(sorted(str(d) for d in device_set))
        if canon not in self._group_keys:
            self._group_keys[canon] = self._next_group
            self._next_group += 1
        return self._group_keys[canon]

    @staticmethod
    def instance_key(var_name: str) -> int:
        digest = hashlib.md5(var_name.encode()).hexdigest()
        return int(digest, 16) % MAX_INT32
