"""Synchronizer base class.

Analog of reference ``autodist/kernel/synchronization/synchronizer.py:23-104``:
holds the cluster context (replica count, worker id, chief-ness) and provides
the factory-by-name ``create``. Where the reference's synchronizers rewrite
graph edges (``in_graph_apply``/``between_graph_apply``), ours contribute a
gradient transform to the lowered SPMD step: ``sync(grad, state) ->
(synced_grad_in_storage_layout, new_state)``. The reference's two phases map
onto TPU as: in-graph apply = the intra-mesh collective (one XLA op spans
all local replicas); between-graph apply = the same collective spanning
hosts over ICI/DCN — SPMD erases the distinction, which is precisely why the
reference's AllReduce ``between_graph_apply`` was already a no-op
(``all_reduce_synchronizer.py:199-201``).
"""
from abc import ABC, abstractmethod

import jax

from autodist_tpu import const


class Synchronizer(ABC):
    def __init__(self, var_name: str, config, num_replicas: int,
                 mesh_axis: str = const.DATA_AXIS, layout=None,
                 extra_axes: tuple = (), dcn_axes: tuple = ()):
        self.var_name = var_name
        self.config = config
        self.num_replicas = num_replicas  # TOTAL devices reducing this grad
        self.mesh_axis = mesh_axis        # axis carrying partitioned shards
        self.extra_axes = tuple(extra_axes)  # further axes (seq, ...) to reduce
        self.dcn_axes = tuple(dcn_axes)   # axes crossing hosts (spec=DCN hint)
        self.layout = layout  # VarLayout

    def psum(self, x):
        return jax.lax.psum(x, (self.mesh_axis,) + self.extra_axes)

    def psum_extra(self, x):
        """Reduce over the non-data axes only (after a data-axis
        reduce-scatter has handled the data axis)."""
        if not self.extra_axes:
            return x
        return jax.lax.psum(x, self.extra_axes)

    @abstractmethod
    def sync(self, grad, state):
        """Inside shard_map: reduce this variable's gradient across the data
        axis, returning it in the variable's *storage* layout (full for
        replicated vars, local shard for partitioned ones)."""

    def state_init(self, grad_shape, dtype):
        """Per-step carried state (compressor residuals); None if stateless."""
        return None

    @staticmethod
    def create(kind_name: str, *args, **kwargs) -> "Synchronizer":
        """Factory by subclass name (reference ``synchronizer.py:90-104``)."""
        from autodist_tpu.kernel.synchronization.all_reduce_synchronizer import (
            AllReduceSynchronizer)
        from autodist_tpu.kernel.synchronization.ps_synchronizer import PSSynchronizer
        subclasses = {c.__name__: c for c in (AllReduceSynchronizer, PSSynchronizer)}
        if kind_name not in subclasses:
            raise ValueError("unknown synchronizer %r" % kind_name)
        return subclasses[kind_name](*args, **kwargs)
