"""Cluster/topology description.

TPU-native analog of reference ``autodist/resource_spec.py:45-331``: parses a
``resource_spec.yml`` describing the machines (here: TPU hosts and their
chips rather than GPU nodes), SSH access groups, chief designation, and
network bandwidth. Adds TPU-specific notions the reference has no need for:
slice topology (ICI-connected chip grid) vs. DCN-connected hosts.

Device naming follows the reference's ``ip:TYPE:index`` convention
(reference ``autodist/resource_spec.py:218-277``), with ``TPU`` as the
accelerator type, e.g. ``10.0.0.1:TPU:0``.
"""
import os
from enum import Enum
from typing import Dict, List, Optional

import yaml

from autodist_tpu.utils import logging

# Default inter-node bandwidth when unspecified: 1 GbE, in bytes/sec
# (mirrors reference resource_spec.py:209-215).
DEFAULT_NETWORK_BANDWIDTH_GBPS = 1
# Default ICI link bandwidth per direction for a v4-like slice, bytes/sec.
DEFAULT_ICI_BANDWIDTH_GBPS = 400
# Per-chip HBM capacity by generation, bytes (public figures); "cpu" is
# host-RAM order for the CPU-mesh development path. The single source of
# truth for every memory budget in the system — the cost model's
# feasibility gate and the ADT5xx static HBM analyzer both read it
# through ResourceSpec.chip_hbm_bytes().
CHIP_HBM_BYTES = {
    "v2": 8e9,
    "v3": 16e9,
    "v4": 32e9,
    "v5e": 16e9,
    "v5p": 95e9,
    "v6e": 32e9,
    "cpu": 64e9,
}


class DeviceType(Enum):
    CPU = "CPU"
    TPU = "TPU"
    # Accepted as a synonym for accelerator chips so reference-format yamls
    # (which say ``gpus:``) parse unchanged.
    GPU = "GPU"


# ----------------------------------------------------- multi-level topology


class TopologyConfigError(ValueError):
    """A ``topology:`` entry holds a value that cannot mean anything.

    Raised at spec-parse time instead of tracebacking mid-build: a typo'd
    ``chips_per_host: 0`` (or a bandwidth of ``-25``) that survived into
    the cost model would surface as a ZeroDivisionError three layers deep
    with no mention of the yaml knob that caused it. Mirrors
    :class:`~autodist_tpu.runtime.elastic.ElasticConfigError`'s named-knob
    message shape so operators grep one pattern."""

    def __init__(self, knob: str, raw, why: str):
        self.knob = knob
        self.raw = raw
        super().__init__(
            "invalid %s=%r: %s (unset it, or set a valid value)"
            % (knob, raw, why))


class TopologyLevel:
    """One link level of the physical hierarchy, innermost (fastest)
    first: ``name`` ("ici", "dcn", ...), ``bandwidth_gbps`` per link and
    direction, and an optional per-step ``budget_ms`` the ADT523 lint
    checks per-level byte estimates against."""

    def __init__(self, name: str, bandwidth_gbps: float,
                 budget_ms: Optional[float] = None):
        self.name = str(name)
        self.bandwidth_gbps = float(bandwidth_gbps)
        self.budget_ms = float(budget_ms) if budget_ms is not None else None

    @property
    def bandwidth_bytes_s(self) -> float:
        return self.bandwidth_gbps * 1e9 / 8.0

    def to_dict(self) -> dict:
        d = {"name": self.name, "bandwidth_gbps": self.bandwidth_gbps}
        if self.budget_ms is not None:
            d["budget_ms"] = self.budget_ms
        return d

    def __repr__(self):
        return "TopologyLevel(%s, %.3g Gbps)" % (self.name,
                                                 self.bandwidth_gbps)


class Topology:
    """First-class multi-level device topology: ``hosts`` x
    ``chips_per_host`` chips with one :class:`TopologyLevel` per link
    tier, innermost first (level 0 = intra-host ICI, level 1 = the
    inter-host network). Device index ``i`` lives on host
    ``i // chips_per_host`` — the contiguous layout every mesh builder
    here emits, and what :meth:`host_of` encodes for the analyzer.

    Loudly validated (:class:`TopologyConfigError`) at construction: a
    malformed hierarchy must fail at spec-parse time with the named yaml
    knob, not traceback mid-build."""

    def __init__(self, hosts: int, chips_per_host: int,
                 levels: List[TopologyLevel]):
        if not isinstance(hosts, int) or hosts < 1:
            raise TopologyConfigError("topology.hosts", hosts,
                                      "must be a positive integer")
        if not isinstance(chips_per_host, int) or chips_per_host < 1:
            raise TopologyConfigError("topology.chips_per_host",
                                      chips_per_host,
                                      "must be a positive integer")
        if not levels:
            raise TopologyConfigError("topology.levels", levels,
                                      "at least one link level is required")
        if hosts > 1 and len(levels) < 2:
            raise TopologyConfigError(
                "topology.levels", [lv.name for lv in levels],
                "a %d-host topology needs an inter-host level (got only "
                "the intra-host level)" % hosts)
        seen = set()
        for i, lv in enumerate(levels):
            knob = "topology.levels[%d].bandwidth_gbps" % i
            bw = lv.bandwidth_gbps
            if not (bw > 0) or bw != bw or bw == float("inf"):
                raise TopologyConfigError(
                    knob, bw, "per-level link bandwidth must be a positive "
                    "finite number")
            if lv.budget_ms is not None and not lv.budget_ms > 0:
                raise TopologyConfigError(
                    "topology.levels[%d].budget_ms" % i, lv.budget_ms,
                    "per-level budget must be a positive number of "
                    "milliseconds")
            if lv.name in seen:
                raise TopologyConfigError("topology.levels[%d].name" % i,
                                          lv.name, "duplicate level name")
            seen.add(lv.name)
        self.hosts = hosts
        self.chips_per_host = chips_per_host
        self.levels = list(levels)

    # ------------------------------------------------------------- geometry

    @property
    def num_devices(self) -> int:
        return self.hosts * self.chips_per_host

    def host_of(self, device_index: int) -> int:
        """Host holding device ``device_index`` (contiguous layout)."""
        if not 0 <= device_index < self.num_devices:
            raise TopologyConfigError(
                "topology", device_index,
                "device index out of range for a %dx%d topology"
                % (self.hosts, self.chips_per_host))
        return device_index // self.chips_per_host

    @property
    def intra_level(self) -> TopologyLevel:
        """The innermost (intra-host) link level."""
        return self.levels[0]

    @property
    def inter_level(self) -> Optional[TopologyLevel]:
        """The inter-host link level; ``None`` on a single-level spec."""
        return self.levels[1] if len(self.levels) > 1 else None

    def level_bandwidth_bytes_s(self, name: str) -> float:
        for lv in self.levels:
            if lv.name == name:
                return lv.bandwidth_bytes_s
        raise TopologyConfigError("topology.levels", name,
                                  "no such level (have %s)"
                                  % [lv.name for lv in self.levels])

    # -------------------------------------------------------- serialization

    def to_dict(self) -> dict:
        return {"hosts": self.hosts, "chips_per_host": self.chips_per_host,
                "levels": [lv.to_dict() for lv in self.levels]}

    @classmethod
    def from_dict(cls, d: dict) -> "Topology":
        """Parse one ``topology:`` section. Accepts ``chips_per_host`` or
        a total ``chips`` count (which must divide evenly across
        ``hosts`` — satellite of ADT524); levels are dicts of
        ``name``/``bandwidth_gbps``(/``budget_ms``), innermost first."""
        if not isinstance(d, dict):
            raise TopologyConfigError("topology", d,
                                      "must be a mapping of hosts/"
                                      "chips_per_host/levels")
        try:
            hosts = int(d.get("hosts", 1))
        except (TypeError, ValueError):
            raise TopologyConfigError("topology.hosts", d.get("hosts"),
                                      "must be a positive integer")
        if "chips_per_host" in d:
            try:
                cph = int(d["chips_per_host"])
            except (TypeError, ValueError):
                raise TopologyConfigError("topology.chips_per_host",
                                          d["chips_per_host"],
                                          "must be a positive integer")
        elif "chips" in d:
            try:
                chips = int(d["chips"])
            except (TypeError, ValueError):
                raise TopologyConfigError("topology.chips", d["chips"],
                                          "must be a positive integer")
            if hosts < 1:
                raise TopologyConfigError("topology.hosts", hosts,
                                          "must be a positive integer")
            if chips < 1 or chips % hosts != 0:
                raise TopologyConfigError(
                    "topology.chips", chips,
                    "total chip count must divide evenly across %d host(s)"
                    % hosts)
            cph = chips // hosts
        else:
            raise TopologyConfigError(
                "topology", sorted(d), "one of chips_per_host or chips is "
                "required")
        raw_levels = d.get("levels")
        if not isinstance(raw_levels, (list, tuple)) or not raw_levels:
            raise TopologyConfigError("topology.levels", raw_levels,
                                      "must be a non-empty list of link "
                                      "levels (innermost first)")
        levels = []
        for i, entry in enumerate(raw_levels):
            if not isinstance(entry, dict) or "bandwidth_gbps" not in entry:
                raise TopologyConfigError(
                    "topology.levels[%d]" % i, entry,
                    "each level needs name and bandwidth_gbps")
            try:
                bw = float(entry["bandwidth_gbps"])
            except (TypeError, ValueError):
                raise TopologyConfigError(
                    "topology.levels[%d].bandwidth_gbps" % i,
                    entry["bandwidth_gbps"], "must be a number")
            budget = entry.get("budget_ms")
            if budget is not None:
                try:
                    budget = float(budget)
                except (TypeError, ValueError):
                    raise TopologyConfigError(
                        "topology.levels[%d].budget_ms" % i,
                        entry.get("budget_ms"), "must be a number")
            levels.append(TopologyLevel(
                entry.get("name", "level%d" % i), bw, budget))
        return cls(hosts, cph, levels)

    @classmethod
    def from_yaml(cls, path: str) -> "Topology":
        """Load a topology from a yaml file — either a bare topology
        mapping or a full resource spec with a ``topology:`` section (the
        analysis CLI's ``--topology FILE`` input)."""
        if not os.path.isfile(path):
            raise TopologyConfigError("topology", path,
                                      "topology spec file not found")
        with open(path, "r") as f:
            d = yaml.safe_load(f) or {}
        if not isinstance(d, dict):
            raise TopologyConfigError("topology", path,
                                      "topology yaml must be a mapping")
        return cls.from_dict(d.get("topology", d))

    def __repr__(self):
        return "Topology(%d hosts x %d chips, levels=%s)" % (
            self.hosts, self.chips_per_host,
            [lv.name for lv in self.levels])


class DeviceSpec:
    """One device: ``<host>:<TYPE>:<index>``."""

    def __init__(self, host: str, device_type: DeviceType = DeviceType.TPU,
                 device_index: int = 0):
        self.host = host
        self.device_type = device_type
        self.device_index = int(device_index)

    def name_string(self) -> str:
        return "{}:{}:{}".format(self.host, self.device_type.value, self.device_index)

    @classmethod
    def from_string(cls, s: str) -> "DeviceSpec":
        parts = s.split(":")
        if len(parts) == 1:
            return cls(parts[0], DeviceType.CPU, 0)
        if len(parts) == 2:
            # "host:0" => TPU index
            return cls(parts[0], DeviceType.TPU, int(parts[1]))
        host, typ, idx = parts[0], parts[1].upper(), parts[2]
        if typ == "GPU":  # normalize reference-style names onto TPU
            typ = "TPU"
        return cls(host, DeviceType[typ], int(idx))

    def __eq__(self, other):
        return isinstance(other, DeviceSpec) and self.name_string() == other.name_string()

    def __hash__(self):
        return hash(self.name_string())

    def __repr__(self):
        return "DeviceSpec({})".format(self.name_string())


class SSHConfig:
    """One SSH access group (reference resource_spec.py:291-331)."""

    def __init__(self, info: dict):
        self.username = info.get("username", "")
        self.port = int(info.get("port", 22))
        self.python_venv = info.get("python_venv", "")
        self.key_file = info.get("key_file", "")
        self.pkey = None
        self.env = dict(info.get("env", {}))
        # "ssh" (default) or "local": local routes remote_exec/remote_copy
        # through bash/cp on this machine — colocated processes (tests,
        # single-host multi-process, loopback nodes) launch for real
        # without an sshd
        self.transport = info.get("transport", "ssh")
        # Make sure remote processes see the TPU runtime.
        self.env.setdefault("PYTHONNOUSERSITE", "True")


class SSHConfigMap(dict):
    def __init__(self, info: Optional[dict], node_groups: Dict[str, str]):
        super().__init__()
        info = info or {}
        for group, conf in info.items():
            self[group] = SSHConfig(conf)
        self._node_groups = node_groups

    def for_host(self, host: str) -> Optional[SSHConfig]:
        group = self._node_groups.get(host)
        return self.get(group) if group else None


class _Node:
    def __init__(self, entry: dict):
        self.address = str(entry["address"])
        # chips/tpus/gpus are synonyms; value may be a count or a list of indices
        raw = entry.get("tpus", entry.get("chips", entry.get("gpus", 0)))
        if isinstance(raw, int):
            self.tpu_indices = list(range(raw))
        else:
            self.tpu_indices = sorted(int(i) for i in (raw or []))
        raw_cpus = entry.get("cpus", [0])
        if isinstance(raw_cpus, int):
            self.cpu_indices = list(range(raw_cpus))
        else:
            self.cpu_indices = sorted(int(i) for i in (raw_cpus or []))
        self.chief = bool(entry.get("chief", False))
        self.ssh_config = entry.get("ssh_config")
        self.network_bandwidth_gbps = float(
            entry.get("network_bandwidth", DEFAULT_NETWORK_BANDWIDTH_GBPS))


class ResourceSpec:
    """Parsed cluster description.

    Construct from a yaml file path (``ResourceSpec("spec.yml")``), a dict
    (``ResourceSpec.from_dict``), or the local process's visible devices
    (``ResourceSpec.from_local``).
    """

    def __init__(self, resource_file: Optional[str] = None):
        self._nodes: "Dict[str, _Node]" = {}
        self._ssh_config_map = SSHConfigMap({}, {})
        self._chief_address: Optional[str] = None
        self._slice_info: dict = {}
        self._topology: Optional[Topology] = None
        if resource_file is not None:
            if not os.path.isfile(resource_file):
                raise FileNotFoundError("resource spec file not found: %s" % resource_file)
            with open(resource_file, "r") as f:
                self._from_dict(yaml.safe_load(f) or {})

    @classmethod
    def from_dict(cls, d: dict) -> "ResourceSpec":
        spec = cls()
        spec._from_dict(d)
        return spec

    @classmethod
    def from_local(cls) -> "ResourceSpec":
        """Build a single-node spec from the local JAX runtime's devices."""
        import jax
        n = len(jax.local_devices())
        kind = jax.local_devices()[0].platform.upper() if n else "CPU"
        d = {"nodes": [{"address": "127.0.0.1", "chief": True,
                        "tpus": n if kind != "CPU" else 0,
                        "cpus": list(range(n if kind == "CPU" else 1))}]}
        return cls.from_dict(d)

    def _from_dict(self, d: dict):
        nodes = d.get("nodes", [])
        if not nodes:
            raise ValueError("resource spec has no nodes")
        node_groups = {}
        for entry in nodes:
            node = _Node(entry)
            if node.address in self._nodes:
                raise ValueError("duplicate node address: %s" % node.address)
            self._nodes[node.address] = node
            if node.ssh_config:
                node_groups[node.address] = node.ssh_config
            if node.chief:
                if self._chief_address is not None:
                    raise ValueError("multiple chief nodes")
                self._chief_address = node.address
        if self._chief_address is None:
            # single-node clusters don't need an explicit chief
            if len(self._nodes) == 1:
                self._chief_address = next(iter(self._nodes))
            else:
                raise ValueError("multi-node resource spec must mark one node chief: true")
        self._ssh_config_map = SSHConfigMap(d.get("ssh", {}), node_groups)
        self._slice_info = dict(d.get("slice", {}))
        if d.get("topology") is not None:
            # loud validation at parse time (TopologyConfigError names the
            # yaml knob) — a malformed hierarchy must never reach the cost
            # model as a traceback mid-build
            self._topology = Topology.from_dict(d["topology"])
        logging.debug("ResourceSpec: %d nodes, chief=%s", len(self._nodes), self._chief_address)

    # ------------------------------------------------------------------ props

    @property
    def chief(self) -> str:
        return self._chief_address

    @property
    def node_addresses(self) -> List[str]:
        return sorted(self._nodes.keys())

    @property
    def num_nodes(self) -> int:
        return len(self._nodes)

    @property
    def tpu_devices(self) -> List[DeviceSpec]:
        out = []
        for addr in self.node_addresses:
            for idx in self._nodes[addr].tpu_indices:
                out.append(DeviceSpec(addr, DeviceType.TPU, idx))
        return out

    @property
    def cpu_devices(self) -> List[DeviceSpec]:
        out = []
        for addr in self.node_addresses:
            for idx in self._nodes[addr].cpu_indices:
                out.append(DeviceSpec(addr, DeviceType.CPU, idx))
        return out

    @property
    def devices(self) -> List[DeviceSpec]:
        """All compute devices: TPU chips where present, else CPUs (so
        CPU-only specs still run the full strategy path, mirroring the
        reference's r2/r5 CPU-only specs)."""
        out = []
        for addr in self.node_addresses:
            node = self._nodes[addr]
            if node.tpu_indices:
                out.extend(DeviceSpec(addr, DeviceType.TPU, i) for i in node.tpu_indices)
            else:
                out.extend(DeviceSpec(addr, DeviceType.CPU, i) for i in node.cpu_indices)
        return out

    @property
    def num_tpus(self) -> int:
        return len(self.tpu_devices)

    @property
    def ssh_config_map(self) -> SSHConfigMap:
        return self._ssh_config_map

    @property
    def slice_info(self) -> dict:
        return self._slice_info

    def network_bandwidth_gbps(self, address: str) -> float:
        return self._nodes[address].network_bandwidth_gbps

    def topology(self) -> Optional[Topology]:
        """The explicit multi-level topology (``topology:`` section), or
        ``None`` when the spec declares none — per-level collective
        pricing and the ADT52x analyzer only engage on an explicit
        hierarchy, so flat single-level specs price exactly as before."""
        return self._topology

    def set_topology(self, topology: Optional[Topology]) -> "ResourceSpec":
        """Attach (or clear) the multi-level topology in place — the
        analysis CLI's ``--topology FILE`` hook. Returns self."""
        self._topology = topology
        return self

    def ici_bandwidth_gbps(self) -> float:
        return float(self._slice_info.get("ici_bandwidth", DEFAULT_ICI_BANDWIDTH_GBPS))

    def chip_kind(self) -> str:
        """Chip generation of this cluster ("v4", "v5e", ..., or "cpu"),
        from ``slice.type`` in the yaml; TPU clusters with no declared
        type default to v4, chipless specs to the CPU development path."""
        kind = str(self._slice_info.get("type", "")).lower()
        for k in sorted(CHIP_HBM_BYTES, key=len, reverse=True):
            if k != "cpu" and k in kind:
                return k
        return "v4" if self.num_tpus else "cpu"

    def chip_hbm_bytes(self) -> float:
        """Per-chip HBM capacity in bytes — the memory budget one device's
        params + optimizer state + activations + collective scratch must
        fit. Overridable per cluster via ``slice.hbm_gib`` in the yaml
        (e.g. a partial-HBM MIG-style reservation); defaults to the
        generation's public figure."""
        override = self._slice_info.get("hbm_gib")
        if override is not None:
            return float(override) * (1 << 30)
        return CHIP_HBM_BYTES[self.chip_kind()]

    def node_tpu_count(self, address: str) -> int:
        return len(self._nodes[address].tpu_indices)

    def node_cpu_count(self, address: str) -> int:
        return len(self._nodes[address].cpu_indices)

    def is_single_node(self) -> bool:
        return len(self._nodes) == 1

    def without_nodes(self, addresses) -> "ResourceSpec":
        """A copy with ``addresses`` removed — the sync-elastic
        reduced-world restart path (a permanently lost worker is dropped
        and the job resumes on the survivors). The chief is never
        removable: its death ends the job outright."""
        drop = {a for a in addresses if a}
        if not drop:
            return self
        if self._chief_address in drop:
            raise ValueError("cannot exclude the chief node %s"
                             % self._chief_address)
        unknown = drop - set(self._nodes)
        if unknown:
            logging.warning("excluded nodes %s not in the resource spec",
                            sorted(unknown))
        spec = ResourceSpec()
        spec._nodes = {a: n for a, n in self._nodes.items() if a not in drop}
        spec._chief_address = self._chief_address
        spec._ssh_config_map = self._ssh_config_map
        spec._slice_info = dict(self._slice_info)
        spec._topology = self._topology
        logging.warning("resource spec reduced: dropped %s, %d node(s) "
                        "remain", sorted(drop & set(self._nodes)),
                        len(spec._nodes))
        return spec

    def __repr__(self):
        return "ResourceSpec(nodes=%s, chief=%s, tpus=%d)" % (
            self.node_addresses, self.chief, self.num_tpus)
