"""Cluster/topology description.

TPU-native analog of reference ``autodist/resource_spec.py:45-331``: parses a
``resource_spec.yml`` describing the machines (here: TPU hosts and their
chips rather than GPU nodes), SSH access groups, chief designation, and
network bandwidth. Adds TPU-specific notions the reference has no need for:
slice topology (ICI-connected chip grid) vs. DCN-connected hosts.

Device naming follows the reference's ``ip:TYPE:index`` convention
(reference ``autodist/resource_spec.py:218-277``), with ``TPU`` as the
accelerator type, e.g. ``10.0.0.1:TPU:0``.
"""
import os
from enum import Enum
from typing import Dict, List, Optional

import yaml

from autodist_tpu.utils import logging

# Default inter-node bandwidth when unspecified: 1 GbE, in bytes/sec
# (mirrors reference resource_spec.py:209-215).
DEFAULT_NETWORK_BANDWIDTH_GBPS = 1
# Default ICI link bandwidth per direction for a v4-like slice, bytes/sec.
DEFAULT_ICI_BANDWIDTH_GBPS = 400
# Per-chip HBM capacity by generation, bytes (public figures); "cpu" is
# host-RAM order for the CPU-mesh development path. The single source of
# truth for every memory budget in the system — the cost model's
# feasibility gate and the ADT5xx static HBM analyzer both read it
# through ResourceSpec.chip_hbm_bytes().
CHIP_HBM_BYTES = {
    "v2": 8e9,
    "v3": 16e9,
    "v4": 32e9,
    "v5e": 16e9,
    "v5p": 95e9,
    "v6e": 32e9,
    "cpu": 64e9,
}


class DeviceType(Enum):
    CPU = "CPU"
    TPU = "TPU"
    # Accepted as a synonym for accelerator chips so reference-format yamls
    # (which say ``gpus:``) parse unchanged.
    GPU = "GPU"


class DeviceSpec:
    """One device: ``<host>:<TYPE>:<index>``."""

    def __init__(self, host: str, device_type: DeviceType = DeviceType.TPU,
                 device_index: int = 0):
        self.host = host
        self.device_type = device_type
        self.device_index = int(device_index)

    def name_string(self) -> str:
        return "{}:{}:{}".format(self.host, self.device_type.value, self.device_index)

    @classmethod
    def from_string(cls, s: str) -> "DeviceSpec":
        parts = s.split(":")
        if len(parts) == 1:
            return cls(parts[0], DeviceType.CPU, 0)
        if len(parts) == 2:
            # "host:0" => TPU index
            return cls(parts[0], DeviceType.TPU, int(parts[1]))
        host, typ, idx = parts[0], parts[1].upper(), parts[2]
        if typ == "GPU":  # normalize reference-style names onto TPU
            typ = "TPU"
        return cls(host, DeviceType[typ], int(idx))

    def __eq__(self, other):
        return isinstance(other, DeviceSpec) and self.name_string() == other.name_string()

    def __hash__(self):
        return hash(self.name_string())

    def __repr__(self):
        return "DeviceSpec({})".format(self.name_string())


class SSHConfig:
    """One SSH access group (reference resource_spec.py:291-331)."""

    def __init__(self, info: dict):
        self.username = info.get("username", "")
        self.port = int(info.get("port", 22))
        self.python_venv = info.get("python_venv", "")
        self.key_file = info.get("key_file", "")
        self.pkey = None
        self.env = dict(info.get("env", {}))
        # "ssh" (default) or "local": local routes remote_exec/remote_copy
        # through bash/cp on this machine — colocated processes (tests,
        # single-host multi-process, loopback nodes) launch for real
        # without an sshd
        self.transport = info.get("transport", "ssh")
        # Make sure remote processes see the TPU runtime.
        self.env.setdefault("PYTHONNOUSERSITE", "True")


class SSHConfigMap(dict):
    def __init__(self, info: Optional[dict], node_groups: Dict[str, str]):
        super().__init__()
        info = info or {}
        for group, conf in info.items():
            self[group] = SSHConfig(conf)
        self._node_groups = node_groups

    def for_host(self, host: str) -> Optional[SSHConfig]:
        group = self._node_groups.get(host)
        return self.get(group) if group else None


class _Node:
    def __init__(self, entry: dict):
        self.address = str(entry["address"])
        # chips/tpus/gpus are synonyms; value may be a count or a list of indices
        raw = entry.get("tpus", entry.get("chips", entry.get("gpus", 0)))
        if isinstance(raw, int):
            self.tpu_indices = list(range(raw))
        else:
            self.tpu_indices = sorted(int(i) for i in (raw or []))
        raw_cpus = entry.get("cpus", [0])
        if isinstance(raw_cpus, int):
            self.cpu_indices = list(range(raw_cpus))
        else:
            self.cpu_indices = sorted(int(i) for i in (raw_cpus or []))
        self.chief = bool(entry.get("chief", False))
        self.ssh_config = entry.get("ssh_config")
        self.network_bandwidth_gbps = float(
            entry.get("network_bandwidth", DEFAULT_NETWORK_BANDWIDTH_GBPS))


class ResourceSpec:
    """Parsed cluster description.

    Construct from a yaml file path (``ResourceSpec("spec.yml")``), a dict
    (``ResourceSpec.from_dict``), or the local process's visible devices
    (``ResourceSpec.from_local``).
    """

    def __init__(self, resource_file: Optional[str] = None):
        self._nodes: "Dict[str, _Node]" = {}
        self._ssh_config_map = SSHConfigMap({}, {})
        self._chief_address: Optional[str] = None
        self._slice_info: dict = {}
        if resource_file is not None:
            if not os.path.isfile(resource_file):
                raise FileNotFoundError("resource spec file not found: %s" % resource_file)
            with open(resource_file, "r") as f:
                self._from_dict(yaml.safe_load(f) or {})

    @classmethod
    def from_dict(cls, d: dict) -> "ResourceSpec":
        spec = cls()
        spec._from_dict(d)
        return spec

    @classmethod
    def from_local(cls) -> "ResourceSpec":
        """Build a single-node spec from the local JAX runtime's devices."""
        import jax
        n = len(jax.local_devices())
        kind = jax.local_devices()[0].platform.upper() if n else "CPU"
        d = {"nodes": [{"address": "127.0.0.1", "chief": True,
                        "tpus": n if kind != "CPU" else 0,
                        "cpus": list(range(n if kind == "CPU" else 1))}]}
        return cls.from_dict(d)

    def _from_dict(self, d: dict):
        nodes = d.get("nodes", [])
        if not nodes:
            raise ValueError("resource spec has no nodes")
        node_groups = {}
        for entry in nodes:
            node = _Node(entry)
            if node.address in self._nodes:
                raise ValueError("duplicate node address: %s" % node.address)
            self._nodes[node.address] = node
            if node.ssh_config:
                node_groups[node.address] = node.ssh_config
            if node.chief:
                if self._chief_address is not None:
                    raise ValueError("multiple chief nodes")
                self._chief_address = node.address
        if self._chief_address is None:
            # single-node clusters don't need an explicit chief
            if len(self._nodes) == 1:
                self._chief_address = next(iter(self._nodes))
            else:
                raise ValueError("multi-node resource spec must mark one node chief: true")
        self._ssh_config_map = SSHConfigMap(d.get("ssh", {}), node_groups)
        self._slice_info = dict(d.get("slice", {}))
        logging.debug("ResourceSpec: %d nodes, chief=%s", len(self._nodes), self._chief_address)

    # ------------------------------------------------------------------ props

    @property
    def chief(self) -> str:
        return self._chief_address

    @property
    def node_addresses(self) -> List[str]:
        return sorted(self._nodes.keys())

    @property
    def num_nodes(self) -> int:
        return len(self._nodes)

    @property
    def tpu_devices(self) -> List[DeviceSpec]:
        out = []
        for addr in self.node_addresses:
            for idx in self._nodes[addr].tpu_indices:
                out.append(DeviceSpec(addr, DeviceType.TPU, idx))
        return out

    @property
    def cpu_devices(self) -> List[DeviceSpec]:
        out = []
        for addr in self.node_addresses:
            for idx in self._nodes[addr].cpu_indices:
                out.append(DeviceSpec(addr, DeviceType.CPU, idx))
        return out

    @property
    def devices(self) -> List[DeviceSpec]:
        """All compute devices: TPU chips where present, else CPUs (so
        CPU-only specs still run the full strategy path, mirroring the
        reference's r2/r5 CPU-only specs)."""
        out = []
        for addr in self.node_addresses:
            node = self._nodes[addr]
            if node.tpu_indices:
                out.extend(DeviceSpec(addr, DeviceType.TPU, i) for i in node.tpu_indices)
            else:
                out.extend(DeviceSpec(addr, DeviceType.CPU, i) for i in node.cpu_indices)
        return out

    @property
    def num_tpus(self) -> int:
        return len(self.tpu_devices)

    @property
    def ssh_config_map(self) -> SSHConfigMap:
        return self._ssh_config_map

    @property
    def slice_info(self) -> dict:
        return self._slice_info

    def network_bandwidth_gbps(self, address: str) -> float:
        return self._nodes[address].network_bandwidth_gbps

    def ici_bandwidth_gbps(self) -> float:
        return float(self._slice_info.get("ici_bandwidth", DEFAULT_ICI_BANDWIDTH_GBPS))

    def chip_kind(self) -> str:
        """Chip generation of this cluster ("v4", "v5e", ..., or "cpu"),
        from ``slice.type`` in the yaml; TPU clusters with no declared
        type default to v4, chipless specs to the CPU development path."""
        kind = str(self._slice_info.get("type", "")).lower()
        for k in sorted(CHIP_HBM_BYTES, key=len, reverse=True):
            if k != "cpu" and k in kind:
                return k
        return "v4" if self.num_tpus else "cpu"

    def chip_hbm_bytes(self) -> float:
        """Per-chip HBM capacity in bytes — the memory budget one device's
        params + optimizer state + activations + collective scratch must
        fit. Overridable per cluster via ``slice.hbm_gib`` in the yaml
        (e.g. a partial-HBM MIG-style reservation); defaults to the
        generation's public figure."""
        override = self._slice_info.get("hbm_gib")
        if override is not None:
            return float(override) * (1 << 30)
        return CHIP_HBM_BYTES[self.chip_kind()]

    def node_tpu_count(self, address: str) -> int:
        return len(self._nodes[address].tpu_indices)

    def node_cpu_count(self, address: str) -> int:
        return len(self._nodes[address].cpu_indices)

    def is_single_node(self) -> bool:
        return len(self._nodes) == 1

    def without_nodes(self, addresses) -> "ResourceSpec":
        """A copy with ``addresses`` removed — the sync-elastic
        reduced-world restart path (a permanently lost worker is dropped
        and the job resumes on the survivors). The chief is never
        removable: its death ends the job outright."""
        drop = {a for a in addresses if a}
        if not drop:
            return self
        if self._chief_address in drop:
            raise ValueError("cannot exclude the chief node %s"
                             % self._chief_address)
        unknown = drop - set(self._nodes)
        if unknown:
            logging.warning("excluded nodes %s not in the resource spec",
                            sorted(unknown))
        spec = ResourceSpec()
        spec._nodes = {a: n for a, n in self._nodes.items() if a not in drop}
        spec._chief_address = self._chief_address
        spec._ssh_config_map = self._ssh_config_map
        spec._slice_info = dict(self._slice_info)
        logging.warning("resource spec reduced: dropped %s, %d node(s) "
                        "remain", sorted(drop & set(self._nodes)),
                        len(spec._nodes))
        return spec

    def __repr__(self):
        return "ResourceSpec(nodes=%s, chief=%s, tpus=%d)" % (
            self.node_addresses, self.chief, self.num_tpus)
