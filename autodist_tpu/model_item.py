"""ModelItem — the captured training program.

TPU-native analog of reference ``autodist/graph_item.py:218-553``. Where the
reference wraps a ``tf.Graph`` and mines it for gradient/variable/update-op
metadata via op-type tables (``kernel/common/op_info.py``) and optimizer
monkeypatches (``graph_item.py:73-109``), here the program is a pure JAX
function and the metadata comes from *tracing*:

- variables        -> the params pytree (flattened to slash-joined path names)
- gradients        -> ``jax.grad`` of the user's loss function (a pytree that
                      mirrors params exactly — the "grad/target pairs" of
                      ``graph_item.py:301-322`` fall out structurally)
- update ops       -> the optax ``GradientTransformation`` the user passes
                      (its name/args are recorded by ``autodist_tpu.patch``,
                      mirroring ``wrap_optimizer_init``)
- sparse variables -> jaxpr inspection: a param that flows into a ``gather``
                      as the operand being indexed is embedding-like (the
                      analog of the reference detecting ``IndexedSlices``
                      gradients, ``kernel/partitioner.py:660-684``)
"""
import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.tree_util import tree_flatten_with_path, keystr

from autodist_tpu.utils import logging


def _normalize_path(path) -> str:
    """Turn a jax key path into a slash-joined name: ``dense/kernel``."""
    parts = []
    for k in path:
        s = keystr((k,))
        s = s.strip("[]'\". ")
        if s.startswith("'") or s.startswith('"'):
            s = s[1:-1]
        parts.append(s)
    return "/".join(p for p in parts if p)


def flatten_with_names(tree) -> List[Tuple[str, Any]]:
    """Flatten a pytree into (name, leaf) pairs with deterministic order."""
    flat, _ = tree_flatten_with_path(tree)
    return [(_normalize_path(path), leaf) for path, leaf in flat]


def names_of(tree) -> List[str]:
    return [n for n, _ in flatten_with_names(tree)]


@dataclasses.dataclass
class VarInfo:
    """Metadata for one trainable variable."""
    name: str
    shape: Tuple[int, ...]
    dtype: str
    trainable: bool = True
    sparse: bool = False  # embedding-like (gather-indexed) variable

    @property
    def byte_size(self) -> int:
        return int(np.prod(self.shape or (1,))) * np.dtype(self.dtype).itemsize

    @property
    def num_elements(self) -> int:
        return int(np.prod(self.shape or (1,)))

    def to_dict(self):
        return {"name": self.name, "shape": list(self.shape), "dtype": self.dtype,
                "trainable": self.trainable, "sparse": self.sparse}

    @classmethod
    def from_dict(cls, d):
        return cls(name=d["name"], shape=tuple(d["shape"]), dtype=d["dtype"],
                   trainable=d.get("trainable", True), sparse=d.get("sparse", False))


# ------------------------------------------------------------------ sparse detection

from autodist_tpu.kernel.common.op_info import (  # noqa: E402
    TRANSPARENT_PRIMITIVES as _TRANSPARENT_PRIMS)


def _gather_indexed_invars(jaxpr, candidates: set) -> set:
    """Return the subset of ``candidates`` (jaxpr in-vars) that flow, through
    shape-preserving ops, into a ``gather``'s operand-being-indexed.

    This is the recognition step the reference does by looking for
    ``IndexedSlices`` grads / sparse update-op types
    (reference ``kernel/common/op_info.py:73-117``).
    """
    return _gather_indexed_invars_mapped(
        jaxpr, {v: {v} for v in jaxpr.invars if v in candidates})


def _gather_indexed_invars_mapped(jaxpr, invar_roots: Dict[Any, set]) -> set:
    alias: Dict[Any, set] = {v: set(r) for v, r in invar_roots.items()}
    hit = set()

    def roots(atom):
        if hasattr(atom, "val"):
            return set()
        return alias.get(atom, set())

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "gather":
            hit.update(roots(eqn.invars[0]))
        for name, val in eqn.params.items():
            # sub-jaxprs appear as ClosedJaxpr (.jaxpr), as a PLAIN Jaxpr
            # (e.g. shard_map's "jaxpr" param), or in lists of either
            subs = []
            for item in (val if isinstance(val, (list, tuple)) else (val,)):
                if hasattr(item, "jaxpr"):
                    subs.append(item.jaxpr)
                elif hasattr(item, "eqns") and hasattr(item, "invars"):
                    subs.append(item)
            for sub in subs:
                if len(sub.invars) == len(eqn.invars):
                    inner_map = {}
                    for inner_v, outer_a in zip(sub.invars, eqn.invars):
                        r = roots(outer_a)
                        if r:
                            inner_map[inner_v] = r
                    if inner_map:
                        hit.update(_gather_indexed_invars_mapped(sub, inner_map))
        if prim in _TRANSPARENT_PRIMS and eqn.invars:
            r = roots(eqn.invars[0])
            if r:
                for ov in eqn.outvars:
                    alias.setdefault(ov, set()).update(r)
    return hit


def _axis_env_jaxpr(loss_fn: Callable, params, example_batch):
    """Trace with every framework axis name bound (size 1), for loss fns
    that use mesh collectives (``psum("model")``, ``axis_index("seq")``
    in ring attention, ...) and therefore cannot trace bare. Size-1 axes
    leave shapes untouched, and the jaxpr comes out un-wrapped so the
    gather walker sees the same program as inside the step."""
    from autodist_tpu.utils.axis_env import bound_axes
    with bound_axes():
        return jax.make_jaxpr(loss_fn)(params, example_batch)


def detect_sparse_vars(loss_fn: Callable, params, example_batch) -> set:
    """Names of params that are indexed by a ``gather`` in the forward pass."""
    try:
        closed = jax.make_jaxpr(loss_fn)(params, example_batch)
    except Exception:  # noqa: BLE001 — retry under a bound axis env
        try:
            closed = _axis_env_jaxpr(loss_fn, params, example_batch)
        except Exception as e:  # noqa: BLE001 — detection is best-effort
            logging.warning(
                "sparse-var detection failed (%s: %s); treating ALL vars "
                "dense — Parallax will route embeddings to AllReduce and "
                "sparse wire paths stay off; if the model has embedding "
                "tables, fix the trace failure or mark them via "
                "VarInfo.sparse", type(e).__name__, e)
            return set()
    jaxpr = closed.jaxpr
    flat_params, _ = tree_flatten_with_path(params)
    n_param_leaves = len(flat_params)
    param_invars = jaxpr.invars[:n_param_leaves]
    candidates = set(param_invars)
    hits = _gather_indexed_invars(jaxpr, candidates)
    names = []
    for (path, _leaf), invar in zip(flat_params, param_invars):
        if invar in hits:
            names.append(_normalize_path(path))
    return set(names)


# ------------------------------------------------------------------ ModelItem


class ModelItem:
    """The captured program + metadata handed to strategy builders.

    Two capture modes:

    * ``loss_fn`` mode (recommended): the framework owns the train step, so
      strategies can intercept gradients (compression, PS routing, sharded
      weight update). ``loss_fn(params, batch) -> scalar`` (or
      ``(scalar, aux)`` with ``has_aux=True``).
    * ``step_fn`` mode: an opaque user step; strategies can only assign
      shardings (the reference has no analog — its kernels always rewrite the
      graph — but this is the natural JAX low-level escape hatch). Lowered
      by ``GraphTransformer._transform_step_fn`` (jit in/out_shardings from
      the layouts; AllReduce/Partitioned families; entry:
      ``AutoDist.build_step``).
    """

    def __init__(self,
                 loss_fn: Optional[Callable] = None,
                 optimizer=None,
                 params=None,
                 example_batch=None,
                 has_aux: bool = False,
                 step_fn: Optional[Callable] = None,
                 apply_fn: Optional[Callable] = None,
                 trainable_filter: Optional[Callable[[str], bool]] = None,
                 mp_rules=None, mp_meta=None):
        if loss_fn is None and step_fn is None:
            raise ValueError("ModelItem needs loss_fn or step_fn")
        self.loss_fn = loss_fn
        self.step_fn = step_fn
        self.apply_fn = apply_fn
        self.optimizer = optimizer
        self.params = params
        self.example_batch = example_batch
        self.has_aux = has_aux
        # model-parallel sharding rules the model family exports (e.g.
        # models.tp_lm.tp_rules()); registering them lets AutoStrategy
        # enumerate model-parallel candidates for this model — the rules'
        # axis names decide the family (model -> TP, pipe -> PP,
        # expert -> EP; see strategy/auto_strategy.mp_candidates)
        self.mp_rules = list(mp_rules) if mp_rules else None
        # extra search hints: pp_microbatches / pp_schedules the loss was
        # built with, seq_parallel=True when the model's attention shards
        # the sequence dim (ring/Ulysses)
        self.mp_meta = dict(mp_meta) if mp_meta else None
        # default: everything trains except flax's batch_stats collection
        # (BatchNorm running statistics are EMA state, not weights — updating
        # them by gradient would corrupt normalization)
        self.trainable_filter = trainable_filter or (
            lambda name: not (name.startswith("batch_stats/")
                              or "/batch_stats/" in name))
        # filled by patch.py when optimizer construction was captured
        self.optimizer_name: Optional[str] = None
        self.optimizer_args: Dict[str, Any] = {}
        self._var_infos: Optional[Dict[str, VarInfo]] = None
        self._opt_state_spec = None
        if optimizer is not None:
            from autodist_tpu import patch as _patch
            name, args = _patch.lookup_optimizer(optimizer)
            if name:
                self.optimizer_name, self.optimizer_args = name, args

    # ---------------------------------------------------------------- capture

    def prepare(self) -> "ModelItem":
        """Collect variable metadata (analog of ``graph_item.prepare()``,
        reference ``autodist/graph_item.py:494-497``)."""
        if self.params is None:
            raise ValueError("ModelItem.prepare() requires params")
        infos: Dict[str, VarInfo] = {}
        sparse = set()
        if self.loss_fn is not None and self.example_batch is not None:
            loss = self.loss_fn
            if self.has_aux:
                loss = lambda p, b: self.loss_fn(p, b)[0]  # noqa: E731
            sparse = detect_sparse_vars(loss, self.params, self.example_batch)
        for name, leaf in flatten_with_names(self.params):
            arr = jnp.asarray(leaf) if not hasattr(leaf, "shape") else leaf
            infos[name] = VarInfo(
                name=name,
                shape=tuple(arr.shape),
                dtype=str(np.dtype(arr.dtype)),
                trainable=bool(self.trainable_filter(name)),
                sparse=name in sparse,
            )
        self._var_infos = infos
        if self.optimizer is not None:
            self._opt_state_spec = jax.eval_shape(self.optimizer.init, self.params)
        logging.debug("ModelItem.prepare: %d vars (%d sparse)", len(infos), len(sparse))
        return self

    # ---------------------------------------------------------------- queries

    @property
    def var_infos(self) -> Dict[str, VarInfo]:
        if self._var_infos is None:
            self.prepare()
        return self._var_infos

    @property
    def trainable_var_names(self) -> List[str]:
        return [n for n, v in self.var_infos.items() if v.trainable]

    @property
    def sparse_var_names(self) -> List[str]:
        return [n for n, v in self.var_infos.items() if v.sparse]

    @property
    def opt_state_spec(self):
        if self._opt_state_spec is None and self.optimizer is not None and self.params is not None:
            self._opt_state_spec = jax.eval_shape(self.optimizer.init, self.params)
        return self._opt_state_spec

    def grad_fn(self) -> Callable:
        """value_and_grad of the loss — the grad/target pairing of
        reference ``graph_item.py:301-322`` is the returned pytree itself."""
        if self.loss_fn is None:
            raise ValueError("grad_fn requires loss_fn capture mode")
        return jax.value_and_grad(self.loss_fn, has_aux=self.has_aux)

    def total_bytes(self) -> int:
        return sum(v.byte_size for v in self.var_infos.values())

    # ------------------------------------------------------------ serialization

    def to_spec_dict(self) -> dict:
        """Spec-level serialization (analog of graphitem.proto,
        reference ``proto/graphitem.proto:31-48``) — records metadata, not code."""
        return {
            "vars": [v.to_dict() for v in self.var_infos.values()],
            "optimizer_name": self.optimizer_name,
            "optimizer_args": {k: repr(v) for k, v in (self.optimizer_args or {}).items()},
            "has_aux": self.has_aux,
            "mode": "loss_fn" if self.loss_fn is not None else "step_fn",
        }

    def serialize_spec(self) -> bytes:
        return json.dumps(self.to_spec_dict(), sort_keys=True).encode()

    @staticmethod
    def spec_from_bytes(b: bytes) -> dict:
        return json.loads(b.decode())
