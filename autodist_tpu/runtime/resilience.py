"""Resilient control-plane RPC: deadlines, reconnect, idempotent retry.

The raw :class:`~autodist_tpu.runtime.coordination.CoordinationClient` is a
thin blocking socket — any dropped TCP connection, hung RPC, or service
blip surfaces as an ``OSError`` (or hangs forever without a deadline) at
whatever call happened to be in flight. The reference AutoDist never saw
this class of failure because the TF gRPC runtime absorbed it; our
hand-rolled wire needs an explicit policy, which lives here:

- **per-RPC deadlines** — every unary RPC carries ``ADT_RPC_TIMEOUT_S``
  (socket timeout); a hung service turns into a retryable timeout instead
  of an eternal stall. Blocking RPCs (BARRIER, WAITMIN) are exempt: they
  park server-side by design, and their liveness signal is the connection
  itself (a dead service drops it, which the retry loop handles).
- **automatic reconnect with jittered exponential backoff** — transport
  errors drop the connection and retry on a fresh one, up to a per-call
  retry budget (``ADT_RPC_RETRIES``). Jitter is seeded (deterministic
  under test) and prevents a thundering reconnect herd after a service
  restart.
- **a circuit breaker** — ``ADT_BREAKER_FAILURES`` consecutive transport
  failures open the circuit for ``ADT_BREAKER_COOLDOWN_S``; while open,
  calls fail fast with :class:`CircuitOpenError` instead of stacking
  connect timeouts (a worker behind a dead service degrades in bounded
  time to its caller's fallback — e.g. the PS pull's stale-serve window).
- **idempotency tokens** — retrying a side-effecting command (INC, STEP,
  BARRIER, BPUT, QPUSH) after an *ambiguous* drop (request possibly
  applied, reply lost) could double-apply it. Each logical call generates
  one client-unique token, reused verbatim across its retries; the
  service dedups on it and replays the recorded reply (see the
  'Idempotency tokens' section of coordination_service.cc). QPOP has no
  token: a retried pop could silently *re-deliver or lose* a gradient
  blob, so it is **at-most-once** — only connect-phase failures retry,
  an ambiguous in-flight failure raises to the caller (the async owner
  loop treats it as a transport blip and reconnects; a blob whose pop
  reply died on the wire is a dropped gradient, which pure-async
  semantics tolerate and ``docs/failure_model.md`` documents).

The wrapper exposes the same API surface as ``CoordinationClient`` so it
drops into ``CoordPSService`` factories and the Runner unchanged.
"""
import itertools
import random
import socket
import time
import uuid
from typing import Callable, List, Optional

from autodist_tpu import const
from autodist_tpu.runtime import elastic
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging


class CoordinationUnavailable(ConnectionError):
    """The coordination service stayed unreachable past the retry budget.

    Subclasses ``ConnectionError`` (an ``OSError``) so every existing
    transport-error handler — the watchdog, the async owner loop, the
    heartbeat reconnect — catches it without modification."""


class CircuitOpenError(CoordinationUnavailable):
    """Failing fast: the breaker is open after repeated transport errors."""


class ResilientCoordinationClient:
    """Deadline + retry + idempotency wrapper over ``CoordinationClient``.

    One instance owns (at most) one live connection and is **not** thread
    safe — same contract as the raw client; per-thread instances via a
    factory, exactly how ``CoordPSService`` already works.
    """

    def __init__(self, host: str = "127.0.0.1",
                 port: int = const.DEFAULT_COORDSVC_PORT,
                 rpc_timeout: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 breaker_failures: Optional[int] = None,
                 breaker_cooldown_s: Optional[float] = None,
                 connect_timeout: Optional[float] = None,
                 seed: Optional[int] = None):
        self._host = host
        self._port = port
        if rpc_timeout is None:
            rpc_timeout = const.ENV.ADT_RPC_TIMEOUT_S.val
        self._rpc_timeout = rpc_timeout if rpc_timeout > 0 else None
        self._max_retries = (const.ENV.ADT_RPC_RETRIES.val
                             if max_retries is None else max_retries)
        self._backoff_base_s = backoff_base_s
        self._backoff_max_s = backoff_max_s
        self._breaker_failures = (const.ENV.ADT_BREAKER_FAILURES.val
                                  if breaker_failures is None
                                  else breaker_failures)
        self._breaker_cooldown_s = (const.ENV.ADT_BREAKER_COOLDOWN_S.val
                                    if breaker_cooldown_s is None
                                    else breaker_cooldown_s)
        self._connect_timeout = connect_timeout
        self._rng = random.Random(seed)
        self._client = None
        self._consecutive_failures = 0
        self._breaker_open_until = 0.0
        # token namespace: unique per client instance, monotonic sequence
        # per logical call — a retry reuses the SAME token
        self._token_prefix = uuid.uuid4().hex[:12]
        self._token_seq = itertools.count()
        self.stats = {"retries": 0, "reconnects": 0, "breaker_opens": 0,
                      "deduped_risk_calls": 0}

    # ------------------------------------------------------------ plumbing

    def _new_token(self) -> str:
        return "%s-%d" % (self._token_prefix, next(self._token_seq))

    def _connect(self):
        from autodist_tpu.runtime.coordination import CoordinationClient
        client = CoordinationClient(self._host, self._port,
                                    timeout=self._rpc_timeout,
                                    connect_timeout=self._connect_timeout)
        return client

    def _drop_client(self):
        if self._client is not None:
            try:
                self._client.close()
            except OSError:
                pass
            self._client = None

    def _note_failure(self):
        self._consecutive_failures += 1
        if self._consecutive_failures >= self._breaker_failures and \
                time.monotonic() >= self._breaker_open_until:
            self._breaker_open_until = (time.monotonic()
                                        + self._breaker_cooldown_s)
            self.stats["breaker_opens"] += 1
            tel.counter_add("coord.breaker_opens")
            tel.instant("coord.breaker_open", "coord",
                        target="%s:%d" % (self._host, self._port),
                        failures=self._consecutive_failures)
            logging.warning(
                "coordination circuit OPEN for %.1fs after %d consecutive "
                "transport failures to %s:%d",
                self._breaker_cooldown_s, self._consecutive_failures,
                self._host, self._port)
            # breaker-open is a black-box trigger: the dump preserves the
            # retry/backoff trail and registry state at the moment the
            # control plane was declared down (telemetry/blackbox.py)
            from autodist_tpu.telemetry import blackbox
            blackbox.record("coord.breaker_open",
                            target="%s:%d" % (self._host, self._port),
                            failures=self._consecutive_failures,
                            cooldown_s=self._breaker_cooldown_s)
            blackbox.dump("breaker_open")

    def _check_breaker(self):
        remaining = self._breaker_open_until - time.monotonic()
        if remaining > 0:
            raise CircuitOpenError(
                "coordination service circuit open for another %.1fs "
                "(%s:%d unreachable)" % (remaining, self._host, self._port))

    def _backoff(self, attempt: int):
        delay = min(self._backoff_max_s,
                    self._backoff_base_s * (2 ** attempt))
        # full jitter: [delay/2, delay] — seeded, so fault tests replay
        slept = delay * (0.5 + 0.5 * self._rng.random())
        with tel.span("coord.backoff", "coord", attempt=attempt):
            time.sleep(slept)
        tel.counter_add("coord.backoff_s", slept)

    def _call(self, fn: Callable, op: str, block: bool = False,
              retry_ambiguous: bool = True):
        """Run ``fn(raw_client)`` with reconnect + backoff + breaker.

        ``block=True`` lifts the per-RPC deadline for the call (BARRIER /
        WAITMIN park server-side legitimately). ``retry_ambiguous=False``
        (QPOP) retries only failures raised while CONNECTING — once a
        request may have hit the wire, the error propagates."""
        last_err: Optional[OSError] = None
        for attempt in range(self._max_retries + 1):
            self._check_breaker()
            if attempt:
                self.stats["retries"] += 1
                tel.counter_add("coord.retries")
                tel.instant("coord.retry", "coord", op=op, attempt=attempt)
                self._backoff(attempt - 1)
            try:
                if self._client is None:
                    self._client = self._connect()
                    self.stats["reconnects"] += 1
                    tel.counter_add("coord.reconnects")
            except OSError as e:
                last_err = e
                self._note_failure()
                continue
            client = self._client
            try:
                if block:
                    client.set_rpc_timeout(None)
                try:
                    result = fn(client)
                finally:
                    if block:
                        try:
                            client.set_rpc_timeout(self._rpc_timeout)
                        except OSError:
                            pass  # socket already dead: the except below
                            # (or the caller) owns the real error
            except (OSError, socket.timeout) as e:
                last_err = e
                self._note_failure()
                self._drop_client()
                if not retry_ambiguous:
                    raise
                continue
            self._consecutive_failures = 0
            return result
        raise CoordinationUnavailable(
            "coordination RPC %r to %s:%d failed after %d attempts "
            "(last error: %s)" % (op, self._host, self._port,
                                  self._max_retries + 1, last_err)
        ) from last_err

    # ------------------------------------------------- the client API

    def ping(self) -> bool:
        return self._call(lambda c: c.ping(), "ping")

    def put(self, key: str, value: str):
        # pure overwrite: naturally idempotent, no token needed.
        # Epoch-fenced: KV marks (heartbeat grace, straggler, mirror
        # digests) from a zombie incarnation must not poison the plane.
        elastic.maybe_fence("coord.put")
        return self._call(lambda c: c.put(key, value), "put")

    def get(self, key: str) -> Optional[str]:
        return self._call(lambda c: c.get(key), "get")

    def incr(self, name: str) -> int:
        elastic.maybe_fence("coord.incr")
        token = self._new_token()
        self.stats["deduped_risk_calls"] += 1
        return self._call(lambda c: c.incr(name, token=token), "incr")

    def barrier(self, name: str, num_workers: int):
        # a zombie arriving at a barrier would satisfy an arrival count
        # meant for its replacement — fenced like every mutation
        elastic.maybe_fence("coord.barrier")
        token = self._new_token()
        self.stats["deduped_risk_calls"] += 1
        return self._call(
            lambda c: c.barrier(name, num_workers, token=token),
            "barrier", block=True)

    def report_step(self, worker: str, step: int):
        elastic.maybe_fence("coord.step")
        token = self._new_token()
        self.stats["deduped_risk_calls"] += 1
        return self._call(
            lambda c: c.report_step(worker, step, token=token), "step")

    def min_step(self) -> int:
        return self._call(lambda c: c.min_step(), "min_step")

    def wait_staleness(self, my_step: int, staleness: int):
        # read-blocking: re-running re-evaluates the window, always safe
        return self._call(lambda c: c.wait_staleness(my_step, staleness),
                          "wait_staleness", block=True)

    def goodbye(self, worker: str):
        return self._call(lambda c: c.goodbye(worker), "goodbye")

    def heartbeat(self, worker: str):
        # a zombie's heartbeat would keep its dead identity "alive" at
        # the watchdog across epochs
        elastic.maybe_fence("coord.heartbeat")
        return self._call(lambda c: c.heartbeat(worker), "heartbeat")

    def bput(self, key: str, version: int, payload: bytes):
        elastic.maybe_fence("coord.bput")
        token = self._new_token()
        self.stats["deduped_risk_calls"] += 1
        return self._call(
            lambda c: c.bput(key, version, payload, token=token), "bput")

    def bget(self, key: str):
        return self._call(lambda c: c.bget(key), "bget")

    def qpush(self, queue: str, payload: bytes):
        elastic.maybe_fence("coord.qpush")
        token = self._new_token()
        self.stats["deduped_risk_calls"] += 1
        return self._call(lambda c: c.qpush(queue, payload, token=token),
                          "qpush")

    def qpop(self, queue: str):
        # at-most-once: see the module docstring — no token, no ambiguous
        # retry (a replayed pop would re-deliver; a blind retry would
        # double-pop and lose a blob)
        return self._call(lambda c: c.qpop(queue), "qpop",
                          retry_ambiguous=False)

    def qlen(self, queue: str) -> int:
        return self._call(lambda c: c.qlen(queue), "qlen")

    def dead_workers(self, timeout_s: float) -> List[str]:
        return self._call(lambda c: c.dead_workers(timeout_s),
                          "dead_workers")

    def reconnect(self):
        """Drop the current socket; the next call reconnects. Breaker and
        retry state are kept — this refreshes the transport, it does not
        forgive the service's failure history."""
        self._drop_client()

    def shutdown(self):
        # deliberate one-shot: retrying a shutdown against a service that
        # already exited just burns the whole retry budget on connects
        if self._client is None:
            self._client = self._connect()
        return self._client.shutdown()

    def close(self):
        self._drop_client()
