"""Client + lifecycle for the native coordination service.

The C++ service (``native/coordination/coordination_service.cc``) is the
TPU-native control plane replacing the reference's per-node TF gRPC servers
and its C++ queue/accumulator sync kernels (SURVEY §2.0). This module:

- builds the binary on demand (g++, cached under ``native/build/``),
- starts/stops it (chief-side, the reference's ``server_starter`` role),
- exposes a blocking client: kv, counters, barriers, bounded-staleness
  step windows, heartbeats + dead-worker queries.

The bounded-staleness window is the real implementation of the strategy's
``staleness`` knob across *processes*: each process reports its step and
blocks in ``wait_staleness`` while it is more than ``staleness`` steps ahead
of the slowest worker — the semantics the reference built from size-``s``
token queues (reference ``ps_synchronizer.py:388-458``).
"""
import os
import socket
import subprocess
import time
from typing import List, Optional

from autodist_tpu import const
from autodist_tpu.utils import logging

# native sources live inside the package so installed copies can build too
_NATIVE_DIR = os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "native")
_BINARY = os.path.join(_NATIVE_DIR, "build", "coordination_service")


def build_binary(force: bool = False) -> str:
    """Compile the service with make (cached)."""
    src = os.path.join(_NATIVE_DIR, "coordination", "coordination_service.cc")
    if not force and os.path.exists(_BINARY) and \
            os.path.getmtime(_BINARY) >= os.path.getmtime(src):
        return _BINARY
    logging.info("building coordination service (%s)", src)
    subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                   capture_output=True)
    return _BINARY


class CoordinationServer:
    """Owns a service process (chief-side)."""

    def __init__(self, port: int = const.DEFAULT_COORDINATOR_PORT):
        self.port = port
        self._proc: Optional[subprocess.Popen] = None

    def start(self, wait: Optional[float] = None):
        """Launch the service and wait up to ``wait`` seconds for it to
        answer a ping (default: ``ADT_COORDSVC_START_TIMEOUT_S``, 5s)."""
        if wait is None:
            wait = const.ENV.ADT_COORDSVC_START_TIMEOUT_S.val
        binary = build_binary()
        # detach stdio: the service must not hold the parent's pipes open
        # (a captured-output parent would block on EOF after the chief's
        # own exit, since the service can outlive it)
        self._proc = subprocess.Popen([binary, str(self.port)],
                                      stdin=subprocess.DEVNULL,
                                      stdout=subprocess.DEVNULL,
                                      stderr=subprocess.DEVNULL)
        deadline = time.time() + wait
        while time.time() < deadline:
            try:
                CoordinationClient("127.0.0.1", self.port).ping()
                return self
            except OSError:
                if self._proc.poll() is not None:
                    raise RuntimeError(
                        "coordination service exited with %s (port %d busy?)"
                        % (self._proc.returncode, self.port))
                time.sleep(0.05)
        # don't leak a process that exists but never answered (wait() so
        # the SIGKILLed child is reaped, not left a zombie)
        self._proc.kill()
        try:
            self._proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        self._proc = None
        raise TimeoutError("coordination service did not come up within "
                           "%.1fs (ADT_COORDSVC_START_TIMEOUT_S)" % wait)

    def stop(self):
        if self._proc and self._proc.poll() is None:
            try:
                # finite deadline on BOTH the connect and the reply: a
                # wedged service (accepting but not answering) must fall
                # through to the kill below, not hang stop() forever
                CoordinationClient("127.0.0.1", self.port,
                                   timeout=2.0, connect_timeout=2.0).shutdown()
                self._proc.wait(timeout=2)
            except (OSError, subprocess.TimeoutExpired):
                self._proc.kill()
                try:
                    self._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass  # unreapable (D-state); teardown must not raise
        self._proc = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class CoordinationClient:
    def __init__(self, host: str = "127.0.0.1",
                 port: int = const.DEFAULT_COORDINATOR_PORT,
                 timeout: Optional[float] = None,
                 connect_timeout: Optional[float] = None):
        if connect_timeout is None:
            connect_timeout = const.ENV.ADT_CONNECT_TIMEOUT_S.val
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        self._sock.settimeout(timeout)
        self._buf = b""

    def set_rpc_timeout(self, timeout: Optional[float]):
        """Per-RPC deadline for subsequent calls (None = block forever).
        A call that exceeds it raises ``socket.timeout`` (an OSError) with
        the connection in an indeterminate state — callers must reconnect,
        which is exactly what the resilient wrapper does."""
        self._sock.settimeout(timeout)

    def _recv_line(self) -> str:
        while b"\n" not in self._buf:
            chunk = self._sock.recv(262144)
            if not chunk:
                raise OSError("coordination service closed connection")
            self._buf += chunk
        resp, self._buf = self._buf.split(b"\n", 1)
        return resp.decode().strip()

    def _recv_raw(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(262144)
            if not chunk:
                raise OSError("coordination service closed connection")
            self._buf += chunk
        payload, self._buf = self._buf[:n], self._buf[n:]
        return payload

    def _cmd(self, line: str) -> str:
        self._sock.sendall(line.encode() + b"\n")
        return self._recv_line()

    # must match kMaxBlobBytes in coordination_service.cc — validated here
    # so an oversized payload fails before any bytes hit the wire instead of
    # forcing the service to drain a rejected multi-GB frame
    MAX_BLOB_BYTES = 1 << 31

    def _cmd_raw(self, header: str, payload: bytes) -> str:
        """Length-prefixed binary frame: header line then raw payload
        (the B-suffixed service commands) — no base64 inflation."""
        if len(payload) > self.MAX_BLOB_BYTES:
            raise ValueError(
                "blob payload %d bytes exceeds the service cap %d" %
                (len(payload), self.MAX_BLOB_BYTES))
        self._sock.sendall(header.encode() + b"\n" + payload)
        return self._recv_line()

    # ----------------------------------------------------------------- api

    @staticmethod
    def _token(name: str) -> str:
        """Keys/queue/worker names ride the line protocol as single
        space-separated tokens. Whitespace would shift the argument arity
        — and on the BINARY commands the server would then take the
        unknown-command branch with the payload already in flight,
        parsing raw gradient bytes as command lines (the desync the
        length validation closes for bad lengths). Reject loudly here."""
        if not name or any(c.isspace() for c in name):
            raise ValueError(
                "coordination-service name %r must be non-empty with no "
                "whitespace" % (name,))
        return name

    def _cmd_ok(self, line: str) -> None:
        """Side-effecting RPC that must succeed. NOT an assert: under
        ``python -O`` asserts are stripped WITH their expressions, which
        would silently drop heartbeats, staleness pacing, and barrier
        waits (the RPC itself would never be sent)."""
        resp = self._cmd(line)
        if resp != "OK":
            raise RuntimeError("coordination service rejected %r: %s"
                               % (line.split(" ", 1)[0], resp))

    def ping(self) -> bool:
        return self._cmd("PING") == "PONG"

    def put(self, key: str, value: str):
        self._cmd_ok("PUT %s %s" % (self._token(key), value))

    def get(self, key: str) -> Optional[str]:
        resp = self._cmd("GET %s" % self._token(key))
        return None if resp == "NONE" else resp[4:]

    @staticmethod
    def _tok_suffix(token) -> str:
        """Optional idempotency token: a whitespace-free id the service
        dedups replies on (see coordination_service.cc 'Idempotency
        tokens'). A RETRY of the same logical op must reuse the token."""
        if token is None:
            return ""
        if not token or any(c.isspace() for c in token):
            raise ValueError("idempotency token %r must be non-empty with "
                             "no whitespace" % (token,))
        return " " + token

    def incr(self, name: str, token: Optional[str] = None) -> int:
        return int(self._cmd("INC %s%s" % (self._token(name),
                                           self._tok_suffix(token)))[4:])

    def barrier(self, name: str, num_workers: int,
                token: Optional[str] = None):
        """Block until ``num_workers`` processes reach this barrier."""
        self._cmd_ok("BARRIER %s %d%s" % (self._token(name), num_workers,
                                          self._tok_suffix(token)))

    def report_step(self, worker: str, step: int,
                    token: Optional[str] = None):
        self._cmd_ok("STEP %s %d%s" % (self._token(worker), step,
                                       self._tok_suffix(token)))

    def min_step(self) -> int:
        return int(self._cmd("MINSTEP")[4:])

    def wait_staleness(self, my_step: int, staleness: int):
        """Block while my_step > min_step + staleness (the bounded-staleness
        window; with staleness=0 this is lockstep sync)."""
        self._cmd_ok("WAITMIN %d %d" % (my_step, staleness))

    def goodbye(self, worker: str):
        """Clean deregister: a finished worker must not be counted dead by
        the watchdog nor keep bounding the staleness window."""
        return self._cmd("GOODBYE %s" % self._token(worker))

    def heartbeat(self, worker: str):
        self._cmd_ok("HEARTBEAT %s" % self._token(worker))

    # ---- versioned blobs + FIFO queues (the async-PS wire; payloads are
    #      raw bytes, base64'd on the line protocol)

    def bput(self, key: str, version: int, payload: bytes,
             token: Optional[str] = None):
        """Publish a versioned blob (binary frame — raw bytes on the wire)."""
        resp = self._cmd_raw("BPUTB %s %d %d%s"
                             % (self._token(key), version, len(payload),
                                self._tok_suffix(token)),
                             payload)
        if resp != "OK":
            raise RuntimeError("bput rejected: %s" % resp)

    def bget(self, key: str):
        """(version, payload) of the latest published blob, or None."""
        resp = self._cmd("BGETB %s" % self._token(key))
        if resp == "NONE":
            return None
        _, ver, n = resp.split(" ", 2)
        return int(ver), self._recv_raw(int(n))

    def qpush(self, queue: str, payload: bytes,
              token: Optional[str] = None):
        """Enqueue a blob (binary frame); raises when the service's queue
        cap rejects it (dead-owner backpressure)."""
        resp = self._cmd_raw("QPUSHB %s %d%s"
                             % (self._token(queue), len(payload),
                                self._tok_suffix(token)), payload)
        if resp != "OK":
            raise RuntimeError("qpush rejected: %s" % resp)

    def qpop(self, queue: str):
        resp = self._cmd("QPOPB %s" % self._token(queue))
        if resp == "NONE":
            return None
        return self._recv_raw(int(resp.split(" ", 1)[1]))

    def qlen(self, queue: str) -> int:
        return int(self._cmd("QLEN %s" % self._token(queue))[4:])

    def dead_workers(self, timeout_s: float) -> List[str]:
        resp = self._cmd("DEADLIST %s" % timeout_s)
        return [] if resp == "NONE" else resp[4:].split(",")

    def shutdown(self):
        self._cmd("SHUTDOWN")

    def close(self):
        self._sock.close()
