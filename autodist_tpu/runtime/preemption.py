"""Preemption plane: advance-notice graceful departure.

The elastic plane (``runtime/elastic.py``) recovers from *unplanned*
death: a worker vanishes, the watchdog notices a heartbeat hole, the
survivors shrink. But most departures in a real fleet are ANNOUNCED —
TPU maintenance events, spot/preemptible VM evictions, an operator
draining a host for a kernel upgrade — and treating them as crashes
throws away the one asset a crash never has: the leaver is still alive,
its state is still on the wire, and there is a deadline-sized window to
use both. This module is the planned-departure half:

1. **Notice sources**, all normalized into one
   :class:`PreemptionNotice` published as a KV mark on the coordination
   service (``preempt/notice/<worker>``):

   - **SIGTERM with a deadline** — the universal cloud eviction signal.
     :func:`install_sigterm_notice` arms a handler that records the
     notice locally (signal-safe: one flag write), publishes the mark
     from a helper thread, and chains the PR 10 blackbox dump hook
     deterministically (both fire; the dump runs LAST, so it captures
     the notice in its event tail).
   - **Cloud maintenance-event poll** — ``ADT_MAINTENANCE_FILE`` names a
     path whose existence signals a pending eviction for this host (the
     cloud integration materializes the metadata-server event into it;
     its JSON body may carry ``{"deadline_s": ..., "reason": ...}``).
   - **Operator drain** — ``python -m autodist_tpu.runtime.preemption
     drain <worker> [--deadline S]`` publishes the same mark over the
     coordination service.

2. **Cluster-agreed rescue point** — every Runner polls the notice
   marks at readback boundaries (piggybacked on the elastic epoch poll,
   throttled to ``ADT_PREEMPT_POLL_S``; one ``preempt/seq`` read in the
   steady state). On a fresh notice the chief publishes a rescue *plan*
   (``preempt/plan/<worker>``) naming the step every process saves at;
   at that boundary each process joins the **deadline-budgeted rescue
   checkpoint**: if the remaining grace is below the measured
   ``ckpt.save_ms`` p99 (× a safety factor) the save is SKIPPED
   (``preempt.rescue_skips``) — a checkpoint that cannot commit before
   the SIGKILL would burn the whole window and leave torn debris —
   and the worker goes straight to the handoff.

3. **Planned handoff** — the departing worker stays ALIVE through the
   shrink: the chief's watchdog sees the notice (never a heartbeat
   hole), publishes the survivor roster at epoch+1 *before* the worker
   dies, and the leaver runs every collective up to its final readback
   boundary — so the survivors' live replicas are step-exact and the
   shrink re-shards from memory, never from the last-good checkpoint
   (``ckpt.fallback`` stays untouched). Serving tiers stop admitting,
   drain the in-flight micro-batches, and shed queued work with a typed
   ``Retry-After`` (``ADT_DRAIN_RETRY_AFTER_S``). The leaver then exits
   via :class:`PlannedDeparture` (a ``SystemExit`` with code 0: the
   chief's process watcher reads it as shutdown, not failure).

Protocol keys (all on the native coordination service):

=====================================  ====================================
``preempt/seq``                         bumped on every publish (poll key)
``preempt/notice/<worker>``             the JSON notice (the mark)
``preempt/plan/<worker>``               chief's rescue plan for it
``preempt/left/<worker>``               leaver's "handoff complete" stamp
=====================================  ====================================

Knobs — validated LOUDLY (the PR 12 ``ElasticConfigError`` pattern):
``ADT_PREEMPT_DEADLINE_S`` (default grace when the source attached
none), ``ADT_PREEMPT_POLL_S`` (notice poll period; 0 disables the KV
poll — local SIGTERM/maintenance notices still work), and
``ADT_DRAIN_RETRY_AFTER_S`` (the serving tier's typed Retry-After).
"""
import dataclasses
import json
import os
import threading
import time
from typing import Callable, List, Optional

from autodist_tpu import const
from autodist_tpu.runtime.elastic import ElasticConfigError
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging

SEQ_KEY = "preempt/seq"
NOTICE_PREFIX = "preempt/notice/"
PLAN_PREFIX = "preempt/plan/"
LEFT_PREFIX = "preempt/left/"

# skip the rescue save unless the remaining grace covers the measured
# save p99 with this much headroom (commit is all-or-nothing: a save the
# SIGKILL tears wastes the whole window AND leaves debris to GC)
RESCUE_SAFETY_FACTOR = 1.5

# a notice is GC-stale this long past its deadline (the SIGKILL never
# came — a cancelled maintenance event; the mark must not poison the
# worker's next incarnation)
NOTICE_STALE_AFTER_S = 600.0


def _bump_seq(client):
    """Advance the one-key poll cursor (a KV value, not an INC counter —
    the service's counters live in a different namespace than GET):
    pollers re-scan the per-worker marks only when this changes."""
    client.put(SEQ_KEY, repr(time.time()))


class PlannedDeparture(SystemExit):
    """The graceful exit of a preempted worker: handoff complete, state
    flushed, serving drained. A ``SystemExit`` with code 0 by design —
    the chief's process watcher treats a zero exit as shutdown, never
    failure, so a planned leaver's death aborts nothing."""

    def __init__(self, worker: str, reason: str):
        self.worker = worker
        self.reason = reason
        super().__init__(0)

    def __str__(self):
        return ("planned departure of %s (%s): handoff complete"
                % (self.worker, self.reason))


# ------------------------------------------------------------ knob validation


def validate_preempt_knobs() -> tuple:
    """Parse the preemption knobs LOUDLY; returns ``(deadline_s, poll_s,
    retry_after_s)``. Same contract as
    :func:`~autodist_tpu.runtime.elastic.validate_elastic_knobs`: a
    typo'd knob raises a typed error NAMING it at bring-up — a grace
    window that silently parsed to garbage would surface as a torn
    rescue checkpoint months later."""
    out = []
    for env, lo, what in (
            (const.ENV.ADT_PREEMPT_DEADLINE_S, 1e-9,
             "must be a positive grace window in seconds"),
            (const.ENV.ADT_PREEMPT_POLL_S, 0.0,
             "must be a poll period in seconds (0 disables the KV poll)"),
            (const.ENV.ADT_DRAIN_RETRY_AFTER_S, 0.0,
             "must be a Retry-After in seconds (>= 0)")):
        raw = os.environ.get(env.name_str)
        if raw is None:
            out.append(env.value[2])  # the member's typed default
            continue
        try:
            val = float(raw)
        except ValueError:
            raise ElasticConfigError(env.name_str, raw, what) from None
        if val < lo:
            raise ElasticConfigError(env.name_str, raw, what)
        out.append(val)
    return tuple(out)


# --------------------------------------------------------------- the notice


@dataclasses.dataclass
class PreemptionNotice:
    """One normalized advance notice: ``worker`` is leaving, with
    ``deadline`` (absolute wall clock — the moment the platform may
    SIGKILL) and a human ``reason`` (``sigterm`` / ``maintenance`` /
    ``drain`` / ...)."""

    worker: str
    deadline: float
    reason: str = "unknown"
    announced: float = 0.0

    def remaining_s(self) -> float:
        return self.deadline - time.time()

    def fresh(self) -> bool:
        """A notice stays actionable until its deadline, and stays
        *visible* (for watchdog grace) a while past it; beyond that it
        is GC-stale — the eviction was cancelled or already happened."""
        return time.time() < self.deadline + NOTICE_STALE_AFTER_S

    def to_json(self) -> str:
        return json.dumps({"worker": self.worker,
                           "deadline": round(self.deadline, 6),
                           "reason": self.reason,
                           "announced": round(self.announced, 6)})

    @classmethod
    def from_json(cls, raw: str) -> Optional["PreemptionNotice"]:
        try:
            d = json.loads(raw)
            return cls(worker=str(d["worker"]),
                       deadline=float(d["deadline"]),
                       reason=str(d.get("reason", "unknown")),
                       announced=float(d.get("announced", 0.0)))
        except (ValueError, KeyError, TypeError):
            return None


def publish_notice(client, worker: str, deadline_s: Optional[float] = None,
                   reason: str = "drain") -> PreemptionNotice:
    """Publish an advance notice for ``worker`` (epoch-fenced when a
    membership plane is installed in this process: a zombie must not
    announce departures for the epoch that evicted it)."""
    from autodist_tpu.runtime import elastic
    elastic.maybe_fence("preempt.notice")
    if deadline_s is None:
        deadline_s = validate_preempt_knobs()[0]
    now = time.time()
    notice = PreemptionNotice(worker=worker, deadline=now + float(deadline_s),
                              reason=reason, announced=now)
    client.put(NOTICE_PREFIX + worker, notice.to_json())
    _bump_seq(client)
    tel.counter_add("preempt.notices")
    tel.instant("preempt.notice", "preempt", worker=worker, reason=reason,
                deadline_s=round(float(deadline_s), 3))
    from autodist_tpu.telemetry import blackbox
    blackbox.record("preempt.notice", worker=worker, reason=reason,
                    deadline_s=round(float(deadline_s), 3))
    logging.warning("preemption: %s announced leaving in %.1fs (%s)",
                    worker, deadline_s, reason)
    return notice


def retire_worker(client, worker: str, deadline_s: Optional[float] = None,
                  reason: str = "autoscale") -> int:
    """Planned drain-then-shrink of ONE worker as one move: publish an
    advance preemption notice for ``worker`` (arming its graceful-
    departure path — rescue handoff, serving drain with typed
    Retry-After, zero ``ckpt.fallback``) and then the survivor epoch
    without it. This is the shrink actuator the serving autoscaler
    drives; ``preempt.planned_shrinks`` is counted by the survivors'
    reconfigure path when they adopt the shrunk mesh. Returns the new
    epoch. Raises :class:`RuntimeError` when no epoch is published or
    ``worker`` is not a member (retiring a non-member would burn an
    epoch for nothing)."""
    from autodist_tpu.runtime import elastic
    info = elastic.read_epoch(client)
    if info is None:
        raise RuntimeError(
            "retire_worker(%r): no membership epoch published" % worker)
    epoch, roster = info
    if worker not in roster:
        raise RuntimeError(
            "retire_worker(%r): not in the current roster %s"
            % (worker, roster))
    publish_notice(client, worker, deadline_s=deadline_s, reason=reason)
    survivors = [w for w in roster if w != worker]
    elastic.publish_epoch(client, epoch + 1, survivors)
    from autodist_tpu.telemetry import blackbox
    blackbox.record("preempt.retire", worker=worker, reason=reason,
                    epoch=epoch + 1, survivors=len(survivors))
    return epoch + 1


def read_notice(client, worker: str) -> Optional[PreemptionNotice]:
    raw = client.get(NOTICE_PREFIX + worker)
    if not raw or raw == "0":
        return None
    notice = PreemptionNotice.from_json(raw)
    if notice is None or not notice.fresh():
        return None
    return notice


def clear_notice(client, worker: str):
    """Tombstone a consumed/stale notice (and its plan + left stamps) so
    the worker's next incarnation starts clean."""
    for key in (NOTICE_PREFIX + worker, PLAN_PREFIX + worker,
                LEFT_PREFIX + worker):
        try:
            client.put(key, "0")
        except (OSError, RuntimeError):
            pass


def read_plan(client, worker: str) -> Optional[dict]:
    raw = client.get(PLAN_PREFIX + worker)
    if not raw or raw == "0":
        return None
    try:
        plan = json.loads(raw)
        int(plan["rescue_step"])
        return plan
    except (ValueError, KeyError, TypeError):
        return None


def publish_plan(client, worker: str, rescue_step: int,
                 notice: PreemptionNotice):
    """Chief-side: the cluster-agreed rescue point. Every process saves
    at its first readback boundary at/after ``rescue_step`` — sync jobs
    are collective-lockstep, so that is the SAME step everywhere (the
    save's gathers are collectives and must line up)."""
    client.put(PLAN_PREFIX + worker, json.dumps(
        {"rescue_step": int(rescue_step),
         "deadline": round(notice.deadline, 6), "reason": notice.reason}))
    _bump_seq(client)
    logging.warning("preemption: rescue plan for %s published — every "
                    "process checkpoints at step >= %d (%.1fs of grace "
                    "left)", worker, rescue_step, notice.remaining_s())


def mark_left(client, worker: str):
    client.put(LEFT_PREFIX + worker, repr(time.time()))
    _bump_seq(client)


def has_left(client, worker: str) -> bool:
    raw = client.get(LEFT_PREFIX + worker)
    if not raw or raw == "0":
        return False
    try:
        return float(raw) > 0
    except ValueError:
        return False


# ------------------------------------------------------------ SIGTERM source

# written only by the signal handler, read by guard polls — a plain
# attribute (atomic in CPython); a lock here could self-deadlock the
# handler against the very main thread it interrupts
_signal_notice: Optional[PreemptionNotice] = None
_sigterm_installed = False
_armed_guards: List["PreemptionGuard"] = []


def grace_active() -> bool:
    """True when a preemption guard is armed AND the notice handler is
    actually installed in this process — a SIGTERM is then an advance
    notice consumed by the training loop, not a kill; the blackbox hook
    consults this before re-raising the default disposition. The
    installed-handler half matters: a guard built on a non-main thread
    has no handler, and suppressing the default kill for it would make
    the process silently ignore evictions."""
    return _sigterm_installed and bool(_armed_guards)


def signal_notice() -> Optional[PreemptionNotice]:
    """The notice a SIGTERM delivered to THIS process (None when none)."""
    return _signal_notice


def _publish_signal_notice(notice: PreemptionNotice):
    """Helper-thread half of the SIGTERM handler: everything that takes
    a high-collision lock — logging, the telemetry recorder, the KV mark
    RPC — runs HERE, never in the signal frame (the handler interrupts
    the main thread mid-bytecode; re-entering the recorder/logging locks
    the training loop holds on every step would wedge the process inside
    the handler and burn the whole grace window). The flight-recorder
    EVENT is the one exception kept in the handler: the chained dump
    snapshots the box synchronously and must contain the notice."""
    tel.counter_add("preempt.notices")
    logging.warning(
        "preemption: SIGTERM received — treating it as an advance "
        "notice with %.1fs of grace (rescue checkpoint + graceful "
        "handoff at the next step boundary)",
        max(notice.remaining_s(), 0.0))
    try:
        from autodist_tpu.runtime.coordination import CoordinationClient
        host = (const.ENV.ADT_COORDINATOR_ADDR.val.split(":")[0]
                or "127.0.0.1")
        c = CoordinationClient(host, const.ENV.ADT_COORDSVC_PORT.val,
                               timeout=5.0)
        try:
            c.put(NOTICE_PREFIX + notice.worker, notice.to_json())
            _bump_seq(c)
        finally:
            c.close()
    except (OSError, RuntimeError) as e:
        logging.warning("preemption: could not publish the SIGTERM notice "
                        "(%s); peers learn of the departure from the "
                        "watchdog instead", e)


def install_sigterm_notice() -> bool:
    """Install the SIGTERM-as-advance-notice handler (idempotent; main
    thread only — returns False when it cannot install). Chains whatever
    handler was there before — the PR 10 blackbox dump hook in
    particular — so both fire, dump LAST (the dump's event tail then
    contains the notice; see ``telemetry/blackbox.py`` for the
    reverse-order half of the contract)."""
    global _sigterm_installed
    if _sigterm_installed:
        return True
    if threading.current_thread() is not threading.main_thread():
        return False
    import signal as _signal
    try:
        prev = _signal.getsignal(_signal.SIGTERM)

        def _on_sigterm(signum, frame):
            # SIGNAL FRAME: flag write, the flight-recorder event (one
            # short-held deque lock — the chained dump must snapshot
            # the notice), and the thread spawn. Everything touching a
            # high-collision lock (logging, the telemetry recorder,
            # sockets) belongs to _publish_signal_notice's helper thread.
            global _signal_notice
            deadline_s = validate_preempt_knobs()[0]
            now = time.time()
            worker = const.ENV.ADT_WORKER.val or "chief"
            notice = PreemptionNotice(
                worker=worker, deadline=now + deadline_s,
                reason="sigterm", announced=now)
            _signal_notice = notice
            from autodist_tpu.telemetry import blackbox
            blackbox.record("preempt.notice", worker=worker,
                            reason="sigterm",
                            deadline_s=round(deadline_s, 3))
            threading.Thread(target=_publish_signal_notice, args=(notice,),
                             name="adt-preempt-publish",
                             daemon=True).start()
            # chain the previous handler (the blackbox dump hook) so the
            # dump runs LAST and captures this notice; a notice-aware
            # prev (double-install race) is never re-entered
            if callable(prev) and not getattr(prev, "_adt_notice_handler",
                                              False):
                prev(signum, frame)
            # never re-raise: the grace window owns the process now —
            # the platform's deadline SIGKILL is the backstop

        _on_sigterm._adt_notice_handler = True
        _signal.signal(_signal.SIGTERM, _on_sigterm)
        _sigterm_installed = True
        return True
    except (ValueError, OSError):
        return False  # restricted env / non-main thread


# ----------------------------------------------------- maintenance-event poll


class MaintenancePoller:
    """The cloud maintenance-event hook: ``ADT_MAINTENANCE_FILE`` names
    a path whose EXISTENCE signals a pending eviction of this host (a
    sidecar watches the metadata server — e.g. GCE's
    ``instance/maintenance-event`` — and materializes the event there;
    tests just touch the file). One ``os.path.exists`` per poll; the
    file's JSON body may carry ``deadline_s``/``reason``."""

    def __init__(self, path: Optional[str] = None):
        self._path = (const.ENV.ADT_MAINTENANCE_FILE.val
                      if path is None else path)
        self._consumed = False

    def check(self) -> Optional[PreemptionNotice]:
        if not self._path or self._consumed or not os.path.exists(self._path):
            return None
        try:
            with open(self._path) as f:
                body = json.load(f)
            if not isinstance(body, dict):
                body = {}
        except (OSError, ValueError):
            body = {}  # bare touch file
        # reason and deadline parse INDEPENDENTLY: a body carrying only
        # a reason must not lose it to a missing deadline_s
        reason = str(body.get("reason", "maintenance"))
        try:
            deadline_s = float(body["deadline_s"])
        except (KeyError, TypeError, ValueError):
            deadline_s = validate_preempt_knobs()[0]
        self._consumed = True  # one notice per event file
        now = time.time()
        worker = const.ENV.ADT_WORKER.val or "chief"
        logging.warning("preemption: maintenance event detected at %s — "
                        "%.1fs of grace (%s)", self._path, deadline_s,
                        reason)
        return PreemptionNotice(worker=worker, deadline=now + deadline_s,
                                reason=reason, announced=now)


# ------------------------------------------------------------- runner guard


class PreemptionGuard:
    """One Runner's half of the preemption protocol: poll the notice
    sources at readback boundaries, drive the cluster-agreed rescue
    checkpoint under the deadline budget, and execute the planned
    handoff (serving drain + graceful :class:`PlannedDeparture`) when
    the leaver is this process. Created by every Runner; costs one flag
    check per boundary while no notice is live."""

    def __init__(self, runner, client_factory: Optional[Callable] = None):
        (self.deadline_s, self.poll_s,
         self.retry_after_s) = validate_preempt_knobs()
        self._runner = runner
        # the membership's identity when armed (the ROSTER address —
        # what epochs and operator drains name); the heartbeat identity
        # otherwise. Both are accepted as "this worker" — the chief's
        # roster address and its heartbeat name ("chief") differ.
        m = getattr(runner, "_membership", None)
        hb_name = const.ENV.ADT_WORKER.val or "chief"
        self.worker = m.worker if m is not None else hb_name
        self.aliases = frozenset({self.worker, hb_name})
        self._client_factory = client_factory
        self._maintenance = MaintenancePoller()
        self._poll_at = 0.0
        self._seen_seq = ""
        self._notice: Optional[PreemptionNotice] = None  # being acted on
        self._plan: Optional[dict] = None
        self._rescued = False      # rescue point passed (saved or skipped)
        self._published = False    # self-notice pushed to the service
        self._saver = None
        self.last_handoff_s: Optional[float] = None
        install_sigterm_notice()
        _armed_guards.append(self)

    def close(self):
        try:
            _armed_guards.remove(self)
        except ValueError:
            pass

    # -------------------------------------------------------------- plumbing

    def attach_saver(self, saver):
        """The saver the rescue checkpoint goes through (``fit`` wires
        its periodic saver; default: a fresh one on ``ADT_CKPT_DIR``)."""
        self._saver = saver

    def _rescue_saver(self):
        if self._saver is None:
            from autodist_tpu.checkpoint.saver import Saver
            self._saver = Saver(directory=const.ENV.ADT_CKPT_DIR.val)
        return self._saver

    def _client(self):
        """A coordination client to poll/publish with — whatever the
        runner already opened, else the membership's dedicated client
        factory; None in serviceless (single-process) runs."""
        r = self._runner
        for attr in ("_async_hb", "_coord"):
            c = getattr(r, attr, None)
            if c not in (None, False):
                return c
        return None

    def _with_any_client(self, fn):
        """Run ``fn(client)`` against the runner's client, the wired
        factory, or the membership's; returns None when no service is
        reachable (local-only mode)."""
        c = self._client()
        if c is not None:
            try:
                return fn(c)
            except (OSError, RuntimeError):
                return None
        m = getattr(self._runner, "_membership", None)
        factory = self._client_factory
        if factory is None and m is not None:
            try:
                return m._with_client(fn)
            except (OSError, RuntimeError):
                return None
        if factory is None:
            return None
        try:
            c = factory()
        except (OSError, RuntimeError):
            return None
        try:
            return fn(c)
        except (OSError, RuntimeError):
            return None
        finally:
            try:
                c.close()
            except (OSError, RuntimeError):
                pass

    # ----------------------------------------------------------------- poll

    def poll(self):
        """Readback-boundary notice intake (cheap: local flag + file
        checks always; the KV read is throttled to ``ADT_PREEMPT_POLL_S``
        and is ONE ``preempt/seq`` get until something is published)."""
        if self._notice is None:
            sig = signal_notice()
            if sig is not None:
                self._adopt_notice(sig, local=True)
        if self._notice is None:
            maint = self._maintenance.check()
            if maint is not None:
                tel.counter_add("preempt.notices")
                from autodist_tpu.telemetry import blackbox
                blackbox.record("preempt.notice", worker=maint.worker,
                                reason=maint.reason)
                self._adopt_notice(maint, local=True)
        if self.poll_s <= 0:
            return
        now = time.monotonic()
        if now < self._poll_at:
            return
        self._poll_at = now + self.poll_s

        def read(c):
            seq = c.get(SEQ_KEY) or ""
            if seq == self._seen_seq:
                return None
            members = list(self.aliases)
            m = getattr(self._runner, "_membership", None)
            if m is not None:
                members = list(dict.fromkeys(
                    list(self.aliases) + list(m.roster)))
            found = None
            for w in members:
                n = read_notice(c, w)
                if n is not None and not has_left(c, w):
                    found = n
                    break
            # consume the cursor only after a COMPLETE scan: a transient
            # error mid-scan raises out of here (swallowed by the caller)
            # with the cursor untouched, so the next poll re-scans — a
            # publish must never be permanently missed
            self._seen_seq = seq
            return found
        found = self._with_any_client(read)
        if found is not None and self._notice is None:
            self._adopt_notice(found, local=False)

    def _adopt_notice(self, notice: PreemptionNotice, local: bool):
        self._notice = notice
        self._plan = None
        self._rescued = False
        self._published = not local or notice.reason == "sigterm"
        if notice.worker in self.aliases:
            # keep the epoch fence open for this announced leaver until
            # its deadline: the planned-shrink epoch may land BEFORE our
            # final boundary, and the rescue checkpoint / flush / left
            # stamp must not read as zombie writes mid-collective
            m = getattr(self._runner, "_membership", None)
            if m is not None:
                m.expect_departure(notice.deadline)
        logging.warning(
            "preemption: notice live for %s (%s, %.1fs of grace) — "
            "rescue checkpoint at the agreed boundary, then %s",
            notice.worker, notice.reason, max(notice.remaining_s(), 0.0),
            "graceful handoff" if notice.worker in self.aliases
            else "planned shrink")

    # ------------------------------------------------------------------ act

    @property
    def pending(self) -> bool:
        return self._notice is not None

    def maybe_act(self):
        """Drive the protocol at a SAFE point (no dispatch in flight):
        publish/adopt the rescue plan, take the deadline-budgeted rescue
        checkpoint at the agreed step, and — when the leaver is this
        process — pre-stage the handoff (the actual departure happens in
        ``Runner._maybe_reconfigure`` when the shrink epoch lands, or
        directly here when no membership plane is armed)."""
        notice = self._notice
        if notice is None:
            return
        if not notice.fresh():
            logging.warning("preemption: notice for %s went stale "
                            "(cancelled eviction?) — disarming",
                            notice.worker)
            self._notice = None
            return
        runner = self._runner
        m = getattr(runner, "_membership", None)
        if (notice.worker not in self.aliases and m is not None
                and notice.worker not in m.roster):
            # the announced leaver is out of our (reconfigured) roster:
            # the planned shrink completed — nothing left to stage for
            self._notice = None
            return
        if not self._published and notice.worker in self.aliases:
            # maintenance-file notices reach peers through the mark too
            self._published = True
            self._with_any_client(
                lambda c: c.put(NOTICE_PREFIX + self.worker,
                                notice.to_json()) or _bump_seq(c))
        if self._plan is None:
            self._plan = self._agree_plan(notice)
            if self._plan is None:
                return  # non-chief waiting for the chief's plan
        step = runner._step_count
        if not self._rescued and step >= int(self._plan["rescue_step"]):
            self._rescue(notice)
        if self._rescued and notice.worker in self.aliases:
            m = getattr(runner, "_membership", None)
            solo = m is None or len(m.roster) <= 1
            if solo:
                # no survivors to hand off to: rescue checkpoint is the
                # legacy, drain serving and leave (ADT_AUTO_RESUME picks
                # the job back up elsewhere)
                self.depart(epoch=None, roster=())
            if notice.remaining_s() <= 0:
                # the shrink epoch never arrived inside the grace (no
                # in-run plane, a fail-fast topology, or a chief that
                # declined) — the deadline says this process is going
                # away regardless, and an operator's SIGTERM must not
                # leave an unkillable worker: depart WITHOUT the live
                # handoff; the rescue checkpoint already committed and
                # the unplanned machinery recovers the peers
                logging.warning(
                    "preemption: grace expired with no shrink epoch — "
                    "departing without a live handoff (%s)", notice.reason)
                self.depart(epoch=None, roster=())
            # else: the chief's watchdog publishes the survivor epoch;
            # Runner._maybe_reconfigure routes the excluded leaver here
            # via depart() when it lands. Pre-stage the snapshot so the
            # survivors' reconfigure span carries less work (the planned
            # path's downtime edge over the unplanned shrink).
        elif self._rescued and notice.worker not in self.aliases:
            # pre-stage ONLY at the boundary the reconfigure will run at
            # (the epoch poll already parked it; _maybe_reconfigure is
            # the very next hook) — a per-boundary prestage across the
            # whole notice window would pay a full flush + host
            # snapshot per step just to discard it
            if getattr(runner, "_reconfig_pending", None) is not None:
                runner._prestage_snapshot()

    def _agree_plan(self, notice: PreemptionNotice) -> Optional[dict]:
        """The cluster-agreed rescue step. The chief publishes ``its
        current boundary step`` (sync jobs are collective-lockstep, so
        every process reaches that same boundary); workers adopt the
        published plan. Serviceless runs plan locally."""
        runner = self._runner
        my_step = runner._step_count
        if const.is_chief() or notice.worker in self.aliases:
            plan = {"rescue_step": int(my_step),
                    "deadline": notice.deadline, "reason": notice.reason}
            self._with_any_client(
                lambda c: publish_plan(c, notice.worker, my_step, notice))
            return plan
        return self._with_any_client(
            lambda c: read_plan(c, notice.worker))

    def _rescue(self, notice: PreemptionNotice):
        """The deadline-budgeted rescue checkpoint: save synchronously
        (a rescue that does not COMMIT before the SIGKILL is worthless)
        unless the measured save p99 no longer fits the remaining
        grace."""
        self._rescued = True
        remaining = notice.remaining_s()
        p99_ms = tel.hist_quantile("ckpt.save_ms", 0.99)
        # an already-expired grace skips UNCONDITIONALLY (no p99 needed:
        # any synchronous save now is torn by the SIGKILL) — otherwise
        # skip when the measured p99 no longer fits with headroom
        if remaining <= 0 or (
                p99_ms is not None
                and remaining * 1e3 < p99_ms * RESCUE_SAFETY_FACTOR):
            tel.counter_add("preempt.rescue_skips")
            tel.instant("preempt.rescue_skip", "preempt",
                        remaining_s=round(remaining, 3),
                        save_p99_ms=round(p99_ms or 0.0, 1))
            logging.warning(
                "preemption: SKIPPING the rescue checkpoint — %.2fs of "
                "grace left vs saves measuring %sms at p99 (x%.1f "
                "safety); going straight to the handoff", remaining,
                ("%.0f" % p99_ms) if p99_ms is not None else "unmeasured",
                RESCUE_SAFETY_FACTOR)
            return
        t0 = time.monotonic()
        with tel.span("preempt.rescue_save", "preempt",
                      step=self._runner._step_count,
                      remaining_s=round(remaining, 3)):
            saver = self._rescue_saver()
            saver.save(self._runner)
            saver.wait()  # the commit must land inside the grace window
        save_ms = (time.monotonic() - t0) * 1e3
        tel.counter_add("preempt.rescue_saves")
        tel.hist_observe("preempt.rescue_save_ms", save_ms)
        from autodist_tpu.telemetry import blackbox
        blackbox.record("preempt.rescue_save", worker=notice.worker,
                        step=self._runner._step_count,
                        save_ms=round(save_ms, 1))
        logging.warning("preemption: rescue checkpoint committed at step "
                        "%d in %.0fms (%.2fs of grace left)",
                        self._runner._step_count, save_ms,
                        notice.remaining_s())

    # -------------------------------------------------------------- handoff

    def departing(self) -> bool:
        """True when THIS worker holds a live notice (the Runner's
        reconfigure path asks before treating an epoch that excludes us
        as a zombie fence-out)."""
        n = self._notice
        return n is not None and n.worker in self.aliases and n.fresh()

    def check_departure_now(self) -> bool:
        """UNTHROTTLED departure check for the reconfigure path: the
        chief publishes the shrink epoch right after a notice, and the
        epoch poll (``ADT_ELASTIC_POLL_S``) can observe the exclusion
        before the throttled notice poll (``ADT_PREEMPT_POLL_S``) ever
        adopted the mark — concluding "zombie" there would crash an
        announced leaver with ``FencedOut`` mid-handoff. Consult the KV
        marks directly before the zombie verdict. A departure adopted
        HERE skips the rescue checkpoint by design: its peers are
        already heading into the reconfigure barrier, not into a
        collective save — and the shrink was only published because the
        survivors' live replicas cover the state."""
        if self.departing():
            return True

        def read(c):
            for w in self.aliases:
                n = read_notice(c, w)
                if n is not None:
                    return n
            return None
        found = self._with_any_client(read)
        if found is not None:
            self._adopt_notice(found, local=False)
        return self.departing()

    def depart(self, epoch: Optional[int], roster) -> "PlannedDeparture":
        """The graceful exit: drain serving (typed Retry-After sheds),
        flush training state, stamp ``preempt/left`` so peers and the
        watchdog know the handoff COMPLETED, and raise
        :class:`PlannedDeparture`. Never returns."""
        notice = self._notice
        reason = notice.reason if notice is not None else "drain"
        t0 = time.perf_counter()
        with tel.span("preempt.handoff", "preempt",
                      worker=self.worker, reason=reason,
                      epoch=epoch if epoch is not None else -1,
                      step=self._runner._step_count):
            drained = drain_serving(self.retry_after_s)
            try:
                self._runner.distributed_step.flush_ps()
            except Exception as e:  # noqa: BLE001 — a dead PS pipeline
                # (or the epoch fence on a post-shrink wire write) must
                # not block the departure; the rescue ckpt covers it
                logging.warning("preemption: flush on departure failed "
                                "(%s)", e)
            try:
                # the left stamp may ride a FENCED client — by now the
                # epoch already excludes us, and that is fine: the stamp
                # is the departure protocol's own namespace, best-effort
                self._with_any_client(lambda c: mark_left(c, self.worker))
            except Exception as e:  # noqa: BLE001 — incl. FencedOut
                logging.warning("preemption: left stamp not published "
                                "(%s); the watchdog ages the notice out "
                                "instead", e)
        self.last_handoff_s = time.perf_counter() - t0
        tel.counter_add("preempt.handoffs")
        from autodist_tpu.telemetry import blackbox
        blackbox.record("preempt.handoff", worker=self.worker,
                        reason=reason, drained=drained,
                        downtime_s=round(self.last_handoff_s, 6))
        logging.warning(
            "preemption: %s handed off alive (%s; %d serving request(s) "
            "shed with Retry-After %.1fs) — departing with exit code 0",
            self.worker, reason, drained, self.retry_after_s)
        # the runner is NOT closed here: PlannedDeparture unwinds through
        # fit()'s finally (flush + saver.wait) first, and the runner's
        # exit hook / the departing script's teardown does the close
        # (with its clean GOODBYE) once the unwind completes
        raise PlannedDeparture(self.worker, reason)

    def stats(self) -> dict:
        c = tel.counters()
        n = self._notice
        return {
            "notice": (None if n is None else
                       {"worker": n.worker, "reason": n.reason,
                        "remaining_s": round(n.remaining_s(), 3)}),
            "notices": c.get("preempt.notices", 0.0),
            "rescue_saves": c.get("preempt.rescue_saves", 0.0),
            "rescue_skips": c.get("preempt.rescue_skips", 0.0),
            "handoffs": c.get("preempt.handoffs", 0.0),
            "last_handoff_s": (round(self.last_handoff_s, 6)
                               if self.last_handoff_s is not None else None),
        }


def drain_serving(retry_after_s: Optional[float] = None) -> int:
    """Drain every live serving micro-batcher AND decode engine in this
    process: in-flight groups/sequences complete, queued requests shed
    with the typed Retry-After. Returns the number of shed requests."""
    from autodist_tpu.serving import batcher as batcher_lib
    from autodist_tpu.serving import decode as decode_lib
    shed = 0
    for mb in batcher_lib.active_batchers():
        try:
            shed += mb.drain(retry_after_s=retry_after_s)
        except Exception as e:  # noqa: BLE001 — one wedged batcher must
            # not block the departure of the whole process
            logging.warning("preemption: serving drain failed (%s)", e)
    for de in decode_lib.active_decoders():
        try:
            shed += de.drain(retry_after_s=retry_after_s)
        except Exception as e:  # noqa: BLE001 — same contract for the
            # decode tier: a wedged engine must not block departure
            logging.warning("preemption: decode drain failed (%s)", e)
    return shed


def reset():
    """Test isolation: forget the signal notice and armed guards (the
    installed SIGTERM handler stays — handlers are process state)."""
    global _signal_notice
    _signal_notice = None
    del _armed_guards[:]


# --------------------------------------------------------------- drain CLI


def main(argv: Optional[List[str]] = None) -> int:
    """Operator verbs over the coordination service::

        python -m autodist_tpu.runtime.preemption drain <worker> \\
            [--deadline S] [--reason R] [--host H] [--port P]
        python -m autodist_tpu.runtime.preemption status <worker> [...]

    ``drain`` publishes an advance notice: the worker takes its rescue
    checkpoint, hands off into a planned shrink, and exits cleanly —
    the operator then has the host. ``status`` prints the live
    notice/plan/left marks for a worker."""
    import argparse
    p = argparse.ArgumentParser(prog="python -m "
                                "autodist_tpu.runtime.preemption")
    sub = p.add_subparsers(dest="verb", required=True)
    for verb in ("drain", "status"):
        sp = sub.add_parser(verb)
        sp.add_argument("worker")
        sp.add_argument("--host", default=None)
        sp.add_argument("--port", type=int, default=None)
        if verb == "drain":
            sp.add_argument("--deadline", type=float, default=None,
                            help="grace seconds before the platform may "
                                 "SIGKILL (default ADT_PREEMPT_DEADLINE_S)")
            sp.add_argument("--reason", default="drain")
    args = p.parse_args(argv)
    host = args.host or (const.ENV.ADT_COORDINATOR_ADDR.val.split(":")[0]
                         or "127.0.0.1")
    port = args.port or const.ENV.ADT_COORDSVC_PORT.val
    from autodist_tpu.runtime.coordination import CoordinationClient
    try:
        client = CoordinationClient(host, port, timeout=10.0)
    except OSError as e:
        print("coordination service unreachable at %s:%d: %s"
              % (host, port, e))
        return 1
    try:
        if args.verb == "drain":
            notice = publish_notice(client, args.worker,
                                    deadline_s=args.deadline,
                                    reason=args.reason)
            print("drain published: %s leaves by %s (%s)"
                  % (args.worker,
                     time.strftime("%H:%M:%S",
                                   time.localtime(notice.deadline)),
                     notice.reason))
            return 0
        notice = read_notice(client, args.worker)
        plan = read_plan(client, args.worker)
        left = has_left(client, args.worker)
        print(json.dumps({
            "worker": args.worker,
            "notice": (None if notice is None else
                       json.loads(notice.to_json())),
            "plan": plan, "left": left}, indent=2, sort_keys=True))
        return 0
    finally:
        client.close()


if __name__ == "__main__":
    raise SystemExit(main())
