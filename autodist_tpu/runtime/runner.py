"""Runner / WrappedSession — steady-state execution.

Analog of reference ``autodist/runner.py:78-132``. The reference's
``WrappedSession`` targets the local gRPC TF server, auto-runs initializers,
and routes ``run`` through the Remapper; here the "session" owns the
TrainState, routes feeds/fetches through the Remapper, and invokes the
jitted SPMD step (JAX dispatch to the TPU runtime replaces the gRPC session
client). Step tracing (the reference's chrome-trace dump,
``runner.py:66-75,123-131``) maps to ``jax.profiler`` traces written under
``/tmp/autodist_tpu/traces``.
"""
import itertools
import os
import time
from typing import Any, Optional

import jax
import numpy as np

from autodist_tpu import const
from autodist_tpu.remapper import Remapper
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.train_state import TrainState
from autodist_tpu.utils import logging


class MetricsHandle:
    """Device-resident step metrics from ``Runner.run(sync=False)`` or
    ``Runner.run_superstep``: the dispatch returned immediately, and the
    device→host readback is deferred until :meth:`result` (or any
    mapping-style access — ``handle["loss"]`` — which forces it). This is
    what lets the steady-state loop stay free of per-step host
    round-trips: handles accumulate device-side and one readback
    materializes many steps' metrics at a ``metrics_every`` boundary."""

    __slots__ = ("_device", "_remapper", "_host", "microsteps", "_observer")

    def __init__(self, device_metrics, remapper, microsteps: int = 1,
                 observer=None):
        self._device = device_metrics
        self._remapper = remapper
        self._host = None
        self.microsteps = microsteps
        # called once per MICROSTEP (in order) when the handle
        # materializes — the sentinel's verdict intake; consumed on first
        # result() so re-reads never replay observations
        self._observer = observer

    @property
    def materialized(self) -> bool:
        return self._host is not None

    def result(self):
        """Host metrics (forces the device→host copy on first call).
        Superstep handles return stacked ``[k, ...]`` leaves."""
        if self._host is None:
            with tel.span("runner.readback", "runner",
                          microsteps=self.microsteps):
                self._host = self._remapper.remap_fetch(self._device)
            self._device = None  # free the device buffers
            tel.counter_add("runner.readbacks")
            tel.counter_add("runner.d2h_bytes", sum(
                getattr(np.asarray(leaf), "nbytes", 0)
                for leaf in jax.tree_util.tree_leaves(self._host)))
            if self._observer is not None:
                # consume BEFORE calling: unstack() re-enters result()
                obs, self._observer = self._observer, None
                for m in self.unstack():
                    obs(m)
        return self._host

    def unstack(self) -> list:
        """Per-microstep host metrics — ``microsteps`` dicts of unstacked
        leaves (a length-1 list for plain-step handles)."""
        host = self.result()
        if self.microsteps == 1:
            return [host]
        return [jax.tree_util.tree_map(lambda a, _i=i: np.asarray(a)[_i],
                                       host)
                for i in range(self.microsteps)]

    def __getitem__(self, key):
        return self.result()[key]

    def __iter__(self):
        return iter(self.result())

    def keys(self):
        return self.result().keys()

    def items(self):
        return self.result().items()

    def __repr__(self):
        state = "materialized" if self.materialized else "device-resident"
        return "MetricsHandle(microsteps=%d, %s)" % (self.microsteps, state)


class Runner:
    """Owns a DistributedStep + TrainState and runs steps."""

    def __init__(self, distributed_step, tracing: bool = False,
                 hbm_budget_bytes: Optional[float] = None,
                 sentinel=None):
        self._dstep = distributed_step
        # per-device HBM budget for memory_report(): AutoDist passes the
        # resource spec's chip capacity; a bare Runner has no budget and
        # memory_report only estimates (no ADT501/502 gate)
        self._hbm_budget = hbm_budget_bytes
        self._remapper = Remapper(distributed_step.mesh,
                                  distributed_step.mesh_axis,
                                  seq_axis=distributed_step.seq_axis,
                                  batch_axes=distributed_step.batch_axes,
                                  seq_keys=getattr(distributed_step,
                                                   "seq_feed_keys", None))
        self._tracing = tracing
        self._trace_started = False
        self.state: Optional[TrainState] = None
        # _step_count counts MICROSTEPS (optimizer applies) — the unit the
        # staleness-pacing and mirror-check protocols are defined over; a
        # fused superstep advances it by k. _superstep_count counts jitted
        # dispatches (run/run_superstep calls) — the unit wall-time
        # samples are taken in.
        self._step_count = 0
        self._superstep_count = 0
        # wall time of every run() call (first element includes compile);
        # bounded so week-long jobs don't grow a list forever — the first
        # step and a sliding window of recent steps carry all the signal
        # step_stats() reports
        self._first_step_s: Optional[float] = None
        self._recent_step_s: list = []
        self._total_step_s = 0.0
        self._coord = None
        self._mirror_coord = None
        self._staleness = int(distributed_step.metadata.get("staleness", 0))
        # bounded-staleness pacing is a cross-process property; within one
        # SPMD program all replicas are already lockstep. Async PS paces
        # itself through the parameter service (no step barrier at all).
        if (self._staleness > 0 and const.ENV.ADT_NUM_PROCESSES.val > 1
                and not distributed_step.metadata.get("async")):
            self._coord = self._connect_coordination(
                "staleness pacing (window=%d)" % self._staleness)
        # async multi-process jobs heartbeat time-based so the chief's
        # watchdog can tell a deadlocked-but-alive worker from a healthy
        # one (sync jobs without staleness are collective-lockstep: a
        # wedged peer shows up as a wedged collective, not silence)
        self._async_hb = None
        self._last_hb = 0.0
        self._hb_enabled = (distributed_step.metadata.get("async")
                            and const.ENV.ADT_NUM_PROCESSES.val > 1)
        if self._hb_enabled:
            self._async_hb = self._connect_coordination(
                "async liveness heartbeats")
        self._atexit_cb = None
        if const.ENV.ADT_NUM_PROCESSES.val > 1:
            # goodbye-on-exit: a worker whose script simply ends must
            # deregister, or its last heartbeat ages into a false death.
            # Registered through a weakref so a discarded runner (and its
            # TrainState) is not pinned for the process lifetime; close()
            # unregisters explicitly.
            import atexit
            import weakref
            ref = weakref.ref(self)

            def _close_if_alive(_r=ref):
                runner = _r()
                if runner is not None:
                    runner.close()
            self._atexit_cb = _close_if_alive
            atexit.register(_close_if_alive)
        # ---- training health sentinel (runtime/sentinel.py): None
        # defers to ADT_SENTINEL; an active policy consumes the in-graph
        # verdicts at readback boundaries and drives skip-budget
        # accounting, rollback and save quarantine
        from autodist_tpu.runtime import sentinel as sentinel_lib
        policy = sentinel_lib.resolve_policy(sentinel)
        self._sentinel = (sentinel_lib.Sentinel(policy, self)
                          if policy is not None else None)
        self._sentinel_diags = []
        if self._sentinel is not None:
            from autodist_tpu.analysis import rules as rules_lib
            self._sentinel_diags = rules_lib.verify_sentinel(
                policy, distributed_step.metadata)
            for d in self._sentinel_diags:
                logging.warning("%s", d)
        # one-shot "compiling" grace around first-dispatch compilation:
        # a long XLA compile must not age this worker into a false death
        # at the chief's heartbeat watchdog
        self._compile_grace_marked = False
        self._compile_grace_cleared = False
        # ---- elastic membership plane (runtime/elastic.py): when a
        # membership is installed (in-run elastic jobs), readback
        # boundaries poll the cluster epoch; a bump parks a pending
        # reconfigure that executes at the next SAFE point (never inside
        # a dispatch or metrics materialization)
        from autodist_tpu.runtime import elastic as elastic_lib
        self._membership = elastic_lib.current()
        self._reconfigure_fn = None   # wired by AutoDist (rebuild + re-shard)
        self._reconfig_pending = None  # (epoch, roster) awaiting a safe point
        self._epoch_poll_at = 0.0
        self._last_reconfigure_s = None
        self._reconfigs = 0
        # ---- preemption plane (runtime/preemption.py): advance-notice
        # graceful departure — SIGTERM-with-deadline, maintenance events
        # and operator drains all park a notice the readback boundaries
        # consume (cluster-agreed rescue checkpoint, then planned handoff)
        from autodist_tpu.runtime import preemption as preemption_lib
        self._preempt = preemption_lib.PreemptionGuard(self)
        # (step, snapshot) pre-staged while a planned departure is
        # pending, so the reconfigure span skips the snapshot work
        self._prestaged = None
        # ---- cluster observability plane (telemetry/): arm the flight
        # recorder (always-on bounded black box; also installs the
        # SIGTERM/exit dump hooks per ADT_BLACKBOX*), the online
        # straggler detector, and the fleet-profiling window state
        from autodist_tpu.telemetry import blackbox as blackbox_lib
        from autodist_tpu.telemetry import cluster as cluster_lib
        from autodist_tpu.telemetry import goodput as goodput_lib
        blackbox_lib.get_flight_recorder()
        self._straggler = goodput_lib.StragglerEwma()
        self._straggler_mark_at = 0.0
        # fleet-profiling window: (seq, first_step, last_step) from the
        # coordination-service flag (polled at ADT_PROFILE_POLL_S) or the
        # serviceless ADT_PROFILE_STEPS env; seq 0 = the env window
        env_window = cluster_lib.parse_profile_env(
            const.ENV.ADT_PROFILE_STEPS.val)
        self._profile_window = ((0,) + env_window) if env_window else None
        self._profile_active = False
        self._profile_done_seq = -1
        self._profile_poll_at = 0.0
        self._profile_coord = None  # lazily shares an existing client

    def _connect_coordination(self, purpose: str = "staleness pacing"):
        from autodist_tpu.runtime.coordination import CoordinationClient
        from autodist_tpu.runtime.resilience import (
            ResilientCoordinationClient)
        host = (const.ENV.ADT_COORDINATOR_ADDR.val.split(":")[0]
                or "127.0.0.1")
        port = const.ENV.ADT_COORDSVC_PORT.val
        try:
            # one raw connect as the reachability probe (the resilient
            # client connects lazily and would retry with backoff — too
            # slow a way to learn the service simply is not deployed)
            CoordinationClient(host, port).close()
        except OSError as e:
            logging.warning("coordination service unreachable (%s); "
                            "%s disabled", e, purpose)
            return None
        # steady state rides the resilient client: per-RPC deadlines,
        # reconnect with backoff, idempotent STEP/BARRIER retry — a
        # service blip mid-run degrades to a retried RPC instead of
        # killing pacing/heartbeats with the connection
        logging.info("%s active via %s", purpose, host)
        return ResilientCoordinationClient(host, port)

    @property
    def distributed_step(self):
        return self._dstep

    @property
    def remapper(self):
        return self._remapper

    def init(self, params, opt_state=None) -> TrainState:
        """Initialize distributed state (the reference's auto-run of
        initializers on session creation, ``runner.py:97-100``).

        Under ``ADT_AUTO_RESUME`` (set by the sync-elastic whole-job
        restart, or by the user for at-most-once resume), a committed
        checkpoint in ``ADT_CKPT_DIR`` is restored over the fresh init —
        every process calls init(), so the restore's collective placement
        runs everywhere."""
        m = self._membership
        if m is not None and getattr(m, "joined_late", False):
            # grow-on-join: this worker was admitted into a RUNNING job —
            # the survivors broadcast the run's state (the chief sends at
            # the end of its reconfigure); a fresh init or a stale
            # checkpoint would diverge from the live run
            from autodist_tpu.runtime import elastic as elastic_lib
            snap = elastic_lib.broadcast_state(None)
            elastic_lib.adopt_snapshot(self, snap)
            m.joined_late = False
            try:
                m.ack(m.epoch)
            except OSError:
                pass
            logging.warning("elastic: adopted broadcast state at step %d "
                            "(grow-on-join)", snap.get("step") or 0)
            return self.state
        if const.ENV.ADT_AUTO_RESUME.val:
            # probe BOTH checkpoint formats — a sync-elastic job that
            # checkpoints through ShardedSaver (the scale path) must
            # auto-resume from its shard files, not fail fast because no
            # plain-format meta exists; when both exist, the newer step
            # wins. latest_checkpoint runs the fast integrity validation,
            # so torn save attempts (a crash mid-save is exactly when
            # auto-resume runs) and damaged steps are skipped up front,
            # and restore() falls back further if damage only surfaces
            # while reading (ckpt.fallback counts every skip).
            from autodist_tpu.checkpoint import latest_checkpoint
            _, saver = latest_checkpoint(const.ENV.ADT_CKPT_DIR.val)
            if saver is not None:
                # restore() builds the placed state itself — a fresh
                # init_state first would materialize the whole tree on
                # device just to throw it away
                try:
                    _, step = saver.restore(self)
                except FileNotFoundError as e:
                    # every candidate was skipped as torn/corrupt
                    if const.ENV.ADT_NUM_PROCESSES.val > 1:
                        raise RuntimeError(
                            "ADT_AUTO_RESUME: no valid checkpoint to "
                            "resume from (%s) — peers restoring different "
                            "steps would diverge, refusing to start "
                            "fresh" % e) from e
                    logging.warning("ADT_AUTO_RESUME: %s; starting fresh",
                                    e)
                else:
                    logging.warning("ADT_AUTO_RESUME: restored step %d "
                                    "from %s (%s)", step,
                                    const.ENV.ADT_CKPT_DIR.val,
                                    type(saver).__name__)
                    return self.state
            elif const.ENV.ADT_NUM_PROCESSES.val > 1:
                # one process starting fresh while lockstep peers restore
                # step N diverges every collective — fail loudly (usual
                # cause: the checkpoint dir is not shared across hosts)
                raise RuntimeError(
                    "ADT_AUTO_RESUME is set but no valid committed "
                    "checkpoint exists in %s on this process — a "
                    "multi-process resume needs the checkpoint directory "
                    "shared across hosts (run `python -m "
                    "autodist_tpu.checkpoint ls --dir %s` to inspect)"
                    % (const.ENV.ADT_CKPT_DIR.val,
                       const.ENV.ADT_CKPT_DIR.val))
            else:
                logging.warning("ADT_AUTO_RESUME set but no valid "
                                "checkpoint in %s; starting fresh",
                                const.ENV.ADT_CKPT_DIR.val)
        self.state = self._dstep.init_state(params, opt_state)
        self.notify_state_restored()  # fresh init resets the LR scale
        return self.state

    _RECENT_WINDOW = 512

    @property
    def _heartbeat_every_s(self) -> float:
        # a quarter of the watchdog's window: three missable beats
        return max(0.25, const.ENV.ADT_HEARTBEAT_TIMEOUT_S.val / 4.0)

    def _start_trace_if_due(self):
        # _profile_active: a fleet window already owns jax.profiler — a
        # second start_trace would raise; the first-step trace defers to
        # a later dispatch (self._tracing stays armed)
        if self._tracing and not self._trace_started \
                and not self._profile_active:
            os.makedirs(const.DEFAULT_TRACE_DIR, exist_ok=True)
            jax.profiler.start_trace(os.path.join(
                const.DEFAULT_TRACE_DIR, time.strftime("%Y%m%d-%H%M%S")))
            self._trace_started = True

    def _stop_trace_if_due(self, metrics):
        if self._tracing and self._trace_started:
            jax.block_until_ready(metrics)
            jax.profiler.stop_trace()
            self._trace_started = False
            self._tracing = False  # trace only the first step, like FULL_TRACE runs

    # ------------------------------------------- fleet-coordinated profiling

    def _profile_client(self):
        """A coordination client to poll the fleet profiling flag with —
        reuse whatever this runner already opened (pacing, liveness,
        mirror); never dial a connection just for profiling."""
        for client in (self._coord, self._async_hb, self._mirror_coord):
            if client not in (None, False):
                return client
        return None

    def _maybe_fleet_profile(self):
        """The fleet-profiling window machinery (the generalization of
        the first-step ``tracing=True`` hook above): the chief posts
        "profile steps N..M" on the coordination service
        (``telemetry.request_profile`` / ``python -m
        autodist_tpu.telemetry profile N M``), every worker polls the
        flag at ``ADT_PROFILE_POLL_S``, and each captures a
        ``jax.profiler`` trace for the SAME step window — one
        XLA-level profile per worker, step-aligned with the merged
        telemetry trace it lands next to. ``ADT_PROFILE_STEPS=N:M``
        arms the same window locally without a service.

        Touches LOCAL state only — it runs inside the dispatch span and
        the per-dispatch wall-time sample; the KV poll lives in
        :meth:`_poll_profile_window` (called from ``_after_dispatch``
        next to the other control-plane RPCs) so a retrying poll during
        a service blip neither masquerades as compute time in the
        goodput decomposition nor feeds the straggler EWMA a false
        outlier."""
        if self._profile_window is None:
            return
        seq, first, last = self._profile_window
        step = self._step_count  # the step the NEXT dispatch runs
        if not self._profile_active:
            if first <= step <= last and not self._trace_started:
                worker = const.ENV.ADT_WORKER.val or "chief"
                out = os.path.join(
                    const.DEFAULT_TRACE_DIR,
                    "fleet-%d-%s" % (seq, worker.replace(":", "_")))
                os.makedirs(out, exist_ok=True)
                try:
                    jax.profiler.start_trace(out)
                except RuntimeError as e:  # another trace in flight
                    logging.warning("fleet profiling: start_trace failed "
                                    "(%s) — window #%d skipped", e, seq)
                    self._profile_done_seq = seq
                    self._profile_window = None
                    return
                self._profile_active = True
                tel.counter_add("profiler.windows")
                tel.instant("profiler.window_start", "runner", seq=seq,
                            step=step, first=first, last=last)
                logging.info("fleet profiling: capturing steps %d..%d "
                             "into %s", first, last, out)
            elif step > last:
                # the window is already behind this worker (posted too
                # late, or a rollback rewound past it): never arms
                self._profile_done_seq = max(self._profile_done_seq, seq)
                self._profile_window = None
            return

    def _poll_profile_window(self):
        """Poll the coordination-service profiling flag (at most every
        ``ADT_PROFILE_POLL_S``; 0 disables) and arm a fresh window for
        the NEXT dispatch. Runs in ``_after_dispatch`` with the other
        control-plane RPCs — see :meth:`_maybe_fleet_profile`."""
        poll_s = const.ENV.ADT_PROFILE_POLL_S.val
        if (self._profile_window is not None or self._profile_active
                or poll_s <= 0
                or time.monotonic() < self._profile_poll_at):
            return
        self._profile_poll_at = time.monotonic() + poll_s
        client = self._profile_client()
        if client is None:
            return
        from autodist_tpu.telemetry import cluster as cluster_lib
        with tel.span("runner.profile_poll", "runner"):
            window = cluster_lib.read_profile_window(client)
        if window is not None and window[0] > self._profile_done_seq:
            self._profile_window = window
            logging.info("fleet profiling window #%d armed: "
                         "steps %d..%d", *window)

    def _maybe_fleet_profile_stop(self):
        """Close the window AFTER the dispatch that ran its last step."""
        if not self._profile_active or self._profile_window is None:
            return
        seq, _first, last = self._profile_window
        if self._step_count > last:
            try:
                jax.profiler.stop_trace()
            except RuntimeError:
                pass
            self._profile_active = False
            self._profile_done_seq = max(self._profile_done_seq, seq)
            self._profile_window = None
            tel.instant("profiler.window_stop", "runner", seq=seq,
                        step=self._step_count)

    def _compile_grace_begin(self):
        """Pre-compile heartbeat + one-shot ``compiling`` grace mark,
        sent just before the FIRST dispatch (which carries the XLA
        compile). A fused-k compile of a big bucket can exceed
        ``ADT_HEARTBEAT_TIMEOUT_S`` between step-driven beats, and the
        chief's watchdog would age this healthy worker into a false
        death; the mark (a wall-clock KV record the watchdog checks, see
        ``Coordinator._in_compile_grace``) buys ``ADT_COMPILE_GRACE_S``
        of silence, and is cleared the moment the first dispatch
        returns."""
        if self._superstep_count > 0 or self._compile_grace_marked:
            return
        client = self._async_hb or self._coord
        if client is None:
            return
        worker = const.ENV.ADT_WORKER.val or "chief"
        try:
            client.heartbeat(worker)
            # wall clock, not monotonic: the watchdog runs in ANOTHER
            # process; the grace window is minutes, so host clock skew
            # is noise
            client.put("compiling/%s" % worker, repr(time.time()))
            self._compile_grace_marked = True
            self._last_hb = time.monotonic()
        except (OSError, RuntimeError) as e:
            # best-effort: a rejected/unreachable mark must never stop
            # training — worst case the watchdog sees compile silence
            logging.warning("pre-compile heartbeat failed (%s); the "
                            "watchdog may see a long first compile as "
                            "silence", e)

    def _compile_grace_end(self):
        """Clear the one-shot compiling mark — steady-state silence must
        age normally again."""
        if not self._compile_grace_marked or self._compile_grace_cleared:
            return
        self._compile_grace_cleared = True
        client = self._async_hb or self._coord
        if client is None:
            return
        worker = const.ENV.ADT_WORKER.val or "chief"
        try:
            # "0" = epoch zero: instantly outside any grace window (the
            # line protocol needs a non-empty value token)
            client.put("compiling/%s" % worker, "0")
        except (OSError, RuntimeError):
            pass  # mark ages out via the grace window anyway

    def _maybe_sentinel_act(self):
        """Perform a pending sentinel rollback (or raise the typed
        ``TrainingDiverged``) at a SAFE point — before a dispatch or
        after a readback boundary, never from inside a metrics
        materialization."""
        if self._sentinel is not None:
            self._sentinel.maybe_act()

    # ---------------------------------------- in-run elastic reconfiguration

    def set_reconfigure_handler(self, fn):
        """Wire the rebuild half of an in-run reconfiguration:
        ``fn(runner, epoch, roster, snapshot)`` must re-join the process
        set, rebuild the mesh/programs for it, and re-place the state
        (AutoDist._elastic_reconfigure is the standard handler;
        ``snapshot`` is the in-memory host state, or None when some shard
        had no live local replica — then fall back to the last-good
        checkpoint re-shard)."""
        self._reconfigure_fn = fn

    def adopt_distributed_step(self, dstep):
        """Swap in a rebuilt DistributedStep (post-reconfigure): the
        remapper and staleness metadata follow the new mesh; step/dispatch
        counters and telemetry continue — it is the same logical run."""
        self._dstep = dstep
        self._remapper = Remapper(dstep.mesh, dstep.mesh_axis,
                                  seq_axis=dstep.seq_axis,
                                  batch_axes=dstep.batch_axes,
                                  seq_keys=getattr(dstep, "seq_feed_keys",
                                                   None))
        self._staleness = int(dstep.metadata.get("staleness", 0))

    def _poll_epoch(self):
        """Readback-boundary membership poll (throttled to
        ``ADT_ELASTIC_POLL_S``): a published epoch newer than ours parks a
        pending reconfigure for the next safe point."""
        m = self._membership
        if m is None or self._reconfig_pending is not None:
            return
        now = time.monotonic()
        if now < self._epoch_poll_at:
            return
        self._epoch_poll_at = now + max(0.05,
                                        const.ENV.ADT_ELASTIC_POLL_S.val)
        info = m.peek()
        if info is not None and info[0] > m.epoch:
            self._reconfig_pending = info
            logging.warning(
                "elastic: cluster epoch %d published (we are at %d) — "
                "reconfiguring to %d member(s) at the next boundary",
                info[0], m.epoch, len(info[1]))

    def _maybe_preempt_act(self):
        """Drive a pending preemption notice at a SAFE point (next to the
        sentinel/reconfigure hooks): cluster-agreed rescue checkpoint,
        snapshot pre-staging, and — for a departing worker with no
        membership plane — the graceful exit itself."""
        if self._preempt.pending:
            self._preempt.maybe_act()

    def _prestage_snapshot(self):
        """Pre-stage the in-memory state snapshot for an ANNOUNCED
        membership change (one per boundary step): the leaver is known in
        advance, so the survivors take the flush + snapshot cost here —
        outside the reconfigure span — and the planned handoff's
        recorded downtime carries strictly less work than an unplanned
        shrink's."""
        if (self._prestaged is not None
                and self._prestaged[0] == self._step_count):
            return
        from autodist_tpu.runtime import elastic as elastic_lib
        self._dstep.flush_ps()
        self._prestaged = (self._step_count,
                           elastic_lib.snapshot_runner_state(self))

    def _maybe_reconfigure(self):
        """Execute a pending membership change at a SAFE point (no
        dispatch in flight, metrics all materialized): barrier with the
        other members of the new epoch, snapshot state from live local
        replicas, tear down / re-join the process set via the wired
        handler, and ack. Downtime is the ``elastic.reconfigure`` span."""
        if self._reconfig_pending is None:
            return
        (epoch, roster), self._reconfig_pending = \
            self._reconfig_pending, None
        m = self._membership
        from autodist_tpu.runtime import elastic as elastic_lib
        if m.worker not in roster:
            # UNTHROTTLED notice check: the shrink epoch can outrun the
            # throttled notice poll, and an announced leaver must never
            # take the zombie path
            if self._preempt.check_departure_now():
                # the epoch that excludes us is OUR announced departure:
                # hand off alive (serving drain, state flush, left stamp)
                # and exit gracefully — never the zombie fence-out
                self._preempt.depart(epoch, roster)
            # we were declared dead and survived anyway: a zombie. Every
            # write path is already fenced; this is the loud exit.
            raise elastic_lib.FencedOut("reconfigure", m.epoch, epoch,
                                        m.worker, roster)
        if self._reconfigure_fn is None:
            raise RuntimeError(
                "elastic epoch %d published but no reconfigure handler is "
                "wired on this Runner (AutoDist.build arms it for in-run "
                "elastic jobs)" % epoch)
        t0 = time.perf_counter()
        planned = (self._prestaged is not None
                   and self._prestaged[0] == self._step_count)
        with tel.span("elastic.reconfigure", "elastic", epoch=epoch,
                      world=len(roster), from_world=len(m.roster),
                      step=self._step_count, planned=planned):
            if planned:
                # announced departure: the snapshot was pre-staged at
                # this boundary (outside the span) — the planned path's
                # downtime edge over an unplanned shrink
                snapshot = self._prestaged[1]
            else:
                # land the fused PS carry / in-flight pushes, then
                # snapshot
                self._dstep.flush_ps()
                snapshot = elastic_lib.snapshot_runner_state(self)
            self._prestaged = None
            # superstep-aligned rendezvous of the NEW process set: nobody
            # tears down jax.distributed while a peer is still dispatching
            m.barrier_reconf(epoch, len(roster))
            self._reconfigure_fn(self, epoch, roster, snapshot)
            m.adopt(epoch, roster)
            try:
                m.ack(epoch)
            except OSError:
                logging.warning("elastic: ack for epoch %d failed (the "
                                "chief may escalate)", epoch)
        self._last_reconfigure_s = time.perf_counter() - t0
        self._reconfigs += 1
        tel.counter_add("elastic.reconfigs")
        tel.gauge_set("elastic.epoch", float(epoch))
        from autodist_tpu.telemetry import blackbox
        blackbox.record("elastic.reconfigure", epoch=epoch,
                        world=len(roster),
                        downtime_s=round(self._last_reconfigure_s, 6))
        logging.warning(
            "elastic: reconfigured to epoch %d (%d member(s)) in %.3fs",
            epoch, len(roster), self._last_reconfigure_s)

    def _sentinel_observer(self):
        return self._sentinel.observe if self._sentinel is not None else None

    def sentinel_save_veto(self) -> bool:
        """Consulted by the checkpoint savers: True while the sentinel
        quarantines saves (last verdict bad / rollback pending) — a
        poisoned state must never become the newest committed
        checkpoint."""
        return self._sentinel is not None and self._sentinel.quarantined

    def sentinel_healthy(self) -> bool:
        """The ``healthy`` stamp a checkpoint committed now should carry
        (True when no sentinel is active — an unguarded run has no
        evidence of ill health)."""
        return self._sentinel is None or self._sentinel.healthy()

    @property
    def sentinel(self):
        """The active :class:`~autodist_tpu.runtime.sentinel.Sentinel`
        (None when no policy is armed)."""
        return self._sentinel

    def notify_state_restored(self):
        """Re-sync the PROCESS-LOCAL halves of the sentinel's LR scale
        with the authoritative copy in the (restored or freshly
        initialized) state's sync_state. The scale lives in three
        places — in-graph (``sync_state["sentinel"]["lr_scale"]``, what
        checkpoints persist), ``PSStore.update_scale`` (host applies)
        and ``Sentinel.lr_scale`` (ladder accounting) — and a restore
        replaces only the first; without this hook an auto-resume after
        an escalation would train PS-resident and device-resident vars
        at DIFFERENT effective learning rates. Called by the savers'
        restore paths and by :meth:`init`."""
        scale = 1.0
        sync = getattr(self.state, "sync_state", None)
        if isinstance(sync, dict) and "sentinel" in sync:
            try:
                leaf = sync["sentinel"]["lr_scale"]
                shards = getattr(leaf, "addressable_shards", None)
                if shards:
                    # every shard carries the same scalar; reading a local
                    # shard works even when the global array spans
                    # processes (device_get would refuse it)
                    leaf = shards[0].data
                scale = float(np.asarray(jax.device_get(leaf)).ravel()[0])
            except (KeyError, IndexError, TypeError):
                pass
        store = getattr(self._dstep, "ps_store", None)
        if store is not None:
            store.update_scale = scale
        sen = getattr(self, "_sentinel", None)
        if sen is not None and sen.lr_scale != scale:
            logging.info("sentinel: lr_scale re-synced to %.4g from the "
                         "restored state", scale)
            sen.lr_scale = scale

    def _after_dispatch(self, microsteps: int):
        """Shared post-dispatch control plane: step accounting, liveness
        heartbeat, cross-process staleness pacing and mirror checks — all
        counted in MICROSTEPS, so a fused superstep advances the pacing
        protocol by its true k optimizer applies."""
        self._compile_grace_end()
        self._step_count += microsteps
        self._superstep_count += 1
        tel.counter_add("runner.steps", microsteps)
        tel.counter_add("runner.supersteps")
        self._maybe_fleet_profile_stop()
        self._poll_profile_window()
        self._poll_epoch()
        self._preempt.poll()
        self._maybe_heartbeat()
        if self._coord is not None:
            # bounded staleness across processes (the reference's size-s
            # token-queue semantics, ps_synchronizer.py:388-458): report our
            # step, then block while more than `staleness` ahead of the
            # slowest worker. The wait is a SPAN (collective_wait in the
            # goodput decomposition) with the global step as arg: time
            # parked here is skew caused by a slower peer, and the merged
            # timeline shows exactly which step paid it.
            worker = const.ENV.ADT_WORKER.val or "chief"
            self._coord.report_step(worker, self._step_count)
            self._coord.heartbeat(worker)
            t_bar = time.perf_counter()
            with tel.span("runner.barrier", "runner",
                          step=self._step_count,
                          staleness=self._staleness):
                self._coord.wait_staleness(self._step_count,
                                           self._staleness)
            if self.distributed_step.metadata.get("overlap"):
                # under an overlapped schedule the residual barrier wait
                # IS the exposed (un-hidden) collective time — the number
                # the cost model's overlap_exposed_s predicts and the
                # drift report's overlap row compares against
                tel.counter_add("overlap.exposed_wait_ms",
                                (time.perf_counter() - t_bar) * 1e3)
        self._maybe_check_mirrors()

    def _record_step_time(self, t_begin: float):
        elapsed = time.perf_counter() - t_begin
        self._total_step_s += elapsed
        if self._first_step_s is None:
            self._first_step_s = elapsed  # includes trace + XLA compile
        else:
            self._recent_step_s.append(elapsed)
            if len(self._recent_step_s) > self._RECENT_WINDOW:
                del self._recent_step_s[:len(self._recent_step_s) // 2]
            self._observe_straggler(elapsed)

    def _observe_straggler(self, elapsed: float):
        """Online slow-but-alive detection: sustained EWMA z-score
        outliers in this worker's dispatch wall time flip the
        ``telemetry.straggler`` gauge, emit an instant, and (multi-
        process) mark ``straggler/<worker>`` on the coordination
        service — the chief's watchdog reads the mark to distinguish a
        degraded-but-progressing worker from a dead one instead of
        recycling it (``Coordinator._is_straggling``)."""
        transition = self._straggler.observe(elapsed)
        if transition is None:
            # REFRESH the slow-but-alive mark while still flagged: the
            # watchdog's freshness window (2x heartbeat timeout) must
            # keep seeing a live mark for as long as the degradation
            # lasts — a single flag-time mark would age out and the
            # watchdog would recycle a worker that is still progressing
            if (self._straggler.flagged
                    and time.monotonic() - self._straggler_mark_at
                    > self._heartbeat_every_s):
                self._write_straggler_mark(repr(time.time()))
            return
        if transition == "flag":
            z = self._straggler.last_z
            tel.gauge_set("telemetry.straggler", round(z, 3))
            tel.counter_add("telemetry.straggler_flags")
            tel.instant("telemetry.straggler", "runner", z=round(z, 3),
                        step=self._step_count,
                        dispatch_s=round(elapsed, 6))
            from autodist_tpu.telemetry import blackbox
            blackbox.record("runner.straggler", z=round(z, 3),
                            step=self._step_count,
                            dispatch_s=round(elapsed, 6))
            logging.warning(
                "straggler: dispatch wall time %.4gs is %.1f sigma over "
                "the EWMA baseline for %d consecutive dispatches — "
                "flagging this worker slow-but-alive",
                elapsed, z, self._straggler.patience)
            self._write_straggler_mark(repr(time.time()))
        else:  # "clear"
            tel.gauge_set("telemetry.straggler", 0.0)
            tel.instant("telemetry.straggler_clear", "runner",
                        step=self._step_count)
            self._write_straggler_mark("0")

    def _write_straggler_mark(self, mark: str):
        self._straggler_mark_at = time.monotonic()
        client = self._async_hb or self._coord
        if client is not None:
            worker = const.ENV.ADT_WORKER.val or "chief"
            try:  # best-effort: the mark is advisory, never worth a stall
                client.put("straggler/%s" % worker, mark)
            except (OSError, RuntimeError):
                pass

    def run(self, batch, state: Optional[TrainState] = None,
            sync: bool = True) -> Any:
        """One training step on a host-global batch. ``sync=True``
        (default) returns host metrics, paying one device→host readback
        per step. ``sync=False`` returns a :class:`MetricsHandle` —
        device-resident, materialized lazily — so the steady-state loop
        never re-enters the host between steps; wall-time samples then
        measure dispatch-to-dispatch, not execution (the next forced
        readback re-syncs the clock)."""
        t_begin = time.perf_counter()
        self._maybe_sentinel_act()  # a pending rollback replaces self.state
        self._maybe_preempt_act()   # a pending notice rescues/hands off
        self._maybe_reconfigure()   # a pending epoch re-forms the mesh
        st = state if state is not None else self.state
        if st is None:
            raise RuntimeError("Runner.run before init()")
        self._compile_grace_begin()
        # the global step arg is what makes per-step skew visible on a
        # merged cluster timeline: every worker's dispatch for microstep
        # N carries step=N, so Perfetto (and cluster.step_alignment)
        # lines the tracks up per STEP, not just per run
        with tel.span("runner.dispatch", "runner", microsteps=1, sync=sync,
                      step=self._step_count):
            with tel.span("runner.feed", "runner"):
                sharded_batch = self._remapper.remap_feed(batch)
            self._maybe_fleet_profile()
            self._start_trace_if_due()
            self._check_ps_owner_health()
            # donate only the Runner-owned state; an explicitly-passed state
            # is a caller reference that must stay valid
            new_state, metrics = self._dstep(st, sharded_batch,
                                             donate=state is None)
            if state is None:
                self.state = new_state
            self._after_dispatch(1)
            self._stop_trace_if_due(metrics)
            handle = MetricsHandle(metrics, self._remapper, microsteps=1,
                                   observer=self._sentinel_observer())
            if sync:
                # result() pulls the metrics to host, so the step's device
                # work is complete: this wall time is an honest per-step
                # duration
                host_metrics = handle.result()
                self._record_step_time(t_begin)
                return ((new_state, host_metrics) if state is not None
                        else host_metrics)
            self._record_step_time(t_begin)
            return (new_state, handle) if state is not None else handle

    def run_superstep(self, stacked_batch, sync: bool = False):
        """One FUSED superstep: k microsteps (k = the stacked feed's
        leading dim) in a single donated jitted dispatch
        (``DistributedStep.multi_step``) — gradient collectives, PS
        updates and optimizer applies all stay on device; metrics come
        back stacked ``[k, ...]`` as a lazily-materialized
        :class:`MetricsHandle` (``sync=True`` forces the readback before
        returning). Heartbeats and staleness pacing advance by the true
        k microsteps."""
        t_begin = time.perf_counter()
        self._maybe_sentinel_act()  # a pending rollback replaces self.state
        self._maybe_preempt_act()   # a pending notice rescues/hands off
        self._maybe_reconfigure()   # a pending epoch re-forms the mesh
        if self.state is None:
            raise RuntimeError("Runner.run_superstep before init()")
        self._compile_grace_begin()
        with tel.span("runner.feed", "runner", stacked=True):
            placed = self._remapper.remap_feed_stack(stacked_batch)
        leaves = jax.tree_util.tree_leaves(placed)
        k = int(np.shape(leaves[0])[0]) if leaves else 1
        with tel.span("runner.dispatch", "runner", microsteps=k, sync=sync,
                      step=self._step_count):
            self._maybe_fleet_profile()
            self._start_trace_if_due()
            self._check_ps_owner_health()
            new_state, metrics = self._dstep.run_multi(self.state, placed)
            self.state = new_state
            self._after_dispatch(k)
            self._stop_trace_if_due(metrics)
            handle = MetricsHandle(metrics, self._remapper, microsteps=k,
                                   observer=self._sentinel_observer())
            if sync:
                handle.result()
            self._record_step_time(t_begin)
            return handle.result() if sync else handle

    def lowered_text(self, batch, state: Optional[TrainState] = None,
                     fuse_steps: int = 1, program: str = "train",
                     donate: bool = False) -> str:
        """StableHLO text of the compiled step for ``batch`` — the input
        of the post-lowering lint pass (``analysis/lowered.py``) and the
        static HBM/schedule analyzers (``analysis/hlo.py``,
        ``analysis/memory.py``). Pure lowering: no step runs, host-PS
        values enter as avals. ``program="eval"`` lowers the
        forward-only eval program. With ``fuse_steps=k > 1``, lowers the
        fused k-microstep scan program (the stacked feed is synthesized
        as avals from ``batch``). ``donate=True`` lowers the donated
        variant that actually runs in steady state."""
        st = state if state is not None else self.state
        if st is None:
            raise RuntimeError("Runner.lowered_text before init()")
        placed = self._remapper.remap_feed(batch)
        if fuse_steps > 1 and program == "train":
            stacked = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(
                    (fuse_steps,) + tuple(np.shape(l)), l.dtype), placed)
            return self._dstep.lowered_text(st, stacked,
                                            fuse_steps=fuse_steps,
                                            donate=donate)
        return self._dstep.lowered_text(st, placed, program=program,
                                        donate=donate)

    def lint_lowered(self, batch, state: Optional[TrainState] = None,
                     fuse_steps: int = 1):
        """Run the lowered-program communication checks (ADT405-408) on
        this runner's compiled step; returns the Diagnostic list. With
        ``fuse_steps=k``, lints the fused scan program — ADT408 flags
        per-microstep host transfers inside the scan body."""
        from autodist_tpu.analysis import lowered as lowered_lib
        return lowered_lib.lint_runner(self, batch, state,
                                       fuse_steps=fuse_steps)

    def memory_report(self, batch, state: Optional[TrainState] = None,
                      fuse_steps: int = 1,
                      hbm_budget_bytes: Optional[float] = None,
                      donate: bool = True) -> dict:
        """Static per-device peak-HBM report of the compiled step for
        ``batch`` — buffer sizes from the lowered program's entry
        signature (sharding- and donation-aware) plus a liveness sweep
        for the temporaries, checked against the per-chip HBM budget
        (``ResourceSpec.chip_hbm_bytes()`` via AutoDist, or an explicit
        ``hbm_budget_bytes``). Pure lowering: nothing compiles, nothing
        allocates — OOM surfaces here as an ``ADT501`` diagnostic
        instead of a runtime crash. ``donate=True`` (default) analyzes
        the donated program that actually runs in steady state;
        ``fuse_steps=k`` analyzes the fused superstep program (whose
        un-donated carry is the ``ADT503`` hazard). See
        docs/performance.md for reading the report and sizing budgets."""
        from autodist_tpu.analysis import hlo as hlo_lib
        from autodist_tpu.analysis import memory as memory_lib
        text = self.lowered_text(batch, state, fuse_steps=fuse_steps,
                                 donate=donate)
        program = hlo_lib.parse_hlo_text(text)
        est = memory_lib.estimate_from_text(program)
        schedule = hlo_lib.collective_schedule(program)
        budget = (hbm_budget_bytes if hbm_budget_bytes is not None
                  else self._hbm_budget)
        diags = memory_lib.donation_diagnostics(program,
                                                fuse_steps=fuse_steps)
        report = {
            "program": {"fuse_steps": fuse_steps, "donated": donate,
                        "num_partitions": est.num_partitions},
            "estimate": est.to_dict(),
            "peak_hbm_bytes": round(est.peak_hbm_bytes),
            "peak_hbm_gib": round(est.peak_hbm_bytes / memory_lib.GIB, 4),
            "collectives": {
                "count": len(schedule),
                "per_step_count": len(schedule.per_step()),
                "per_class_payload_bytes":
                    schedule.per_step().class_payload_bytes(),
            },
        }
        if budget is not None:
            diags = diags + memory_lib.budget_diagnostics(
                est.peak_hbm_bytes, budget, source="lowered-program")
            report.update(
                budget_bytes=round(budget),
                budget_gib=round(budget / memory_lib.GIB, 4),
                utilization=(round(est.peak_hbm_bytes / budget, 4)
                             if budget else None))
        report["diagnostics"] = diags
        return report

    def collective_schedule(self, batch, state: Optional[TrainState] = None,
                            program: str = "train", fuse_steps: int = 1):
        """The ordered collective schedule (kind, replica groups, payload
        bytes, loop depth) of one of this runner's compiled programs —
        see ``analysis/hlo.py``."""
        from autodist_tpu.analysis import hlo as hlo_lib
        text = self.lowered_text(batch, state, fuse_steps=fuse_steps,
                                 program=program)
        return hlo_lib.collective_schedule(text)

    def static_profile(self, batch, state: Optional[TrainState] = None,
                       fuse_steps: int = 1, topology=None):
        """Measured per-collective wire bytes of the compiled step — a
        ``StaticCollectiveProfile`` to attach to a ``Simulator`` /
        ``CostModel`` (``attach_static_profile``), replacing the jaxpr
        cost heuristics with what the lowering actually emits. Passing
        the resource spec's ``topology`` additionally attributes each
        replica group's ring edges to the link level they cross
        (``level_wire_bytes`` — the drift report's per-level rows)."""
        from autodist_tpu.simulator.cost_model import StaticCollectiveProfile
        schedule = self.collective_schedule(batch, state,
                                            fuse_steps=fuse_steps)
        n_dev = max(int(getattr(self._dstep.mesh, "size", 1)), 1)
        return StaticCollectiveProfile.from_schedule(
            schedule, default_group_size=n_dev, topology=topology)

    def lint_schedules(self, batch, state: Optional[TrainState] = None,
                       fuse_steps: int = 1):
        """Cross-program collective-schedule consistency (ADT510/511):
        the eval program — and, with ``fuse_steps=k > 1``, the fused
        superstep program's per-microstep body — must embed into the
        train step's schedule, or replicas running different programs on
        the same mesh deadlock in mismatched collectives."""
        from autodist_tpu.analysis import hlo as hlo_lib
        train = self.collective_schedule(batch, state)
        diags = list(hlo_lib.compare_schedules(
            train, self.collective_schedule(batch, state, program="eval"),
            "train", "eval"))
        if fuse_steps > 1:
            diags += hlo_lib.compare_schedules(
                train,
                self.collective_schedule(batch, state,
                                         fuse_steps=fuse_steps),
                "train", "fused")
        return diags

    def step_stats(self) -> dict:
        """Wall-time statistics over this runner's steps (the throughput
        companion to the reference's examples/sec hooks,
        ``examples/benchmark/utils/logs/hooks.py:28``): ``first_step_s``
        isolates trace+compile; ``steady_*`` percentiles describe the
        post-compile regime over a recent window; ``goodput`` is the
        fraction of total stepping wall time the job would have needed at
        steady median speed — compile time, host stalls, and throttle
        windows all show up as lost goodput.

        Fused accounting: wall-time samples are PER DISPATCH, so both
        counts are reported — ``supersteps`` (dispatches: what the timing
        samples and goodput are defined over) and ``microsteps``
        (optimizer applies: what examples/s math must multiply by the
        batch size; ×k under ``fit(fuse_steps=k)``). ``steps`` ==
        ``microsteps`` for backward compatibility (identical without
        fusion). Reading the stats never forces a device sync — under
        ``sync=False`` stepping the samples measure dispatch-to-dispatch
        time, re-synced at every metrics readback boundary.

        The shape is STABLE (a monitoring consumer can rely on every key
        existing): ``steady_*``/``goodput`` are None before any steady
        sample, and ``telemetry`` merges the process-wide registry
        counters (``telemetry/spans.py``) that attribute the wall time —
        jitted dispatches, metric readbacks and their D2H bytes, host-PS
        wire bytes, control-plane retries, prefetcher drops."""
        import statistics
        micro, sup = self._step_count, self._superstep_count
        out = {"steps": micro, "supersteps": sup, "microsteps": micro,
               # which compute tier the step program runs in ("f32" or
               # "bf16") — monitoring needs it to interpret loss jitter
               # and examples/s side by side across precision configs
               "compute_dtype": getattr(
                   getattr(self, "_dstep", None), "metadata",
                   {}).get("compute_dtype", "f32"),
               "total_s": round(self._total_step_s, 6),
               "first_step_s": (round(self._first_step_s, 6)
                                if self._first_step_s is not None else None),
               "steady_median_s": None, "steady_p10_s": None,
               "steady_p90_s": None, "goodput": None}
        recent = self._recent_step_s
        if recent:
            # method="inclusive": the default exclusive method extrapolates
            # past the observed range on small samples (a negative p10
            # after two steps); inclusive keeps percentiles within the data
            qs = (statistics.quantiles(recent, n=10, method="inclusive")
                  if len(recent) >= 2 else [recent[0]] * 9)
            out.update(
                steady_median_s=round(statistics.median(recent), 6),
                steady_p10_s=round(qs[0], 6),
                steady_p90_s=round(qs[-1], 6),
                # goodput is over DISPATCHES: recent samples are
                # per-dispatch durations, so the ideal-time numerator is
                # median x dispatch count, never median x microsteps
                goodput=round(min(1.0, statistics.median(recent) * sup
                              / self._total_step_s), 4)
                if self._total_step_s > 0 else None)
        c = tel.counters()
        out["telemetry"] = {
            "dispatches": c.get("dstep.dispatches", 0.0),
            "readbacks": c.get("runner.readbacks", 0.0),
            "d2h_bytes": c.get("runner.d2h_bytes", 0.0),
            "ps_bytes_pulled": c.get("ps.bytes_pulled", 0.0),
            "ps_bytes_pushed": c.get("ps.bytes_pushed", 0.0),
            "coord_retries": c.get("coord.retries", 0.0),
            "prefetch_dropped_batches": c.get("prefetch.dropped_batches",
                                              0.0),
        }
        # stable sub-dict (same contract as the telemetry merge): every
        # key exists whether or not a sentinel policy is armed (getattr:
        # partially-constructed runners must still report stats)
        sen = getattr(self, "_sentinel", None)
        out["sentinel"] = (sen.stats() if sen is not None else
                           {"skips": 0, "rollbacks": 0,
                            "last_grad_norm": None, "quarantined": False})
        # attributed goodput (telemetry/goodput.py): WHERE the wall time
        # went, not just how much was lost — None with tracing off (the
        # decomposition needs the span tree). Straggler stats are always
        # present (the EWMA runs on wall-time samples, no spans needed).
        straggler = getattr(self, "_straggler", None)
        out["straggler"] = (straggler.stats() if straggler is not None
                            else {"flagged": False, "flags": 0,
                                  "last_z": None, "ewma_s": None})
        report = self.goodput_report()
        out["goodput_breakdown"] = (
            {k: round(v, 6) for k, v in report.buckets.items()}
            if report is not None else None)
        # elastic plane (stable shape): epoch/reconfigure accounting for
        # monitoring and the bench --smoke downtime leg
        m = getattr(self, "_membership", None)
        out["elastic"] = {
            "epoch": m.epoch if m is not None else None,
            "reconfigs": getattr(self, "_reconfigs", 0),
            "last_reconfigure_s": (
                round(self._last_reconfigure_s, 6)
                if getattr(self, "_last_reconfigure_s", None) is not None
                else None),
            "fenced_writes": c.get("elastic.fenced_writes", 0.0),
        }
        # preemption plane (stable shape): notice/rescue/handoff
        # accounting for monitoring and the bench --smoke downtime leg
        guard = getattr(self, "_preempt", None)
        out["preempt"] = (guard.stats() if guard is not None else
                          {"notice": None, "notices": 0.0,
                           "rescue_saves": 0.0, "rescue_skips": 0.0,
                           "handoffs": 0.0, "last_handoff_s": None})
        return out

    def goodput_report(self):
        """The attributed wall-time decomposition of this process's
        training thread (:class:`telemetry.goodput.GoodputReport`):
        compute / collective-wait / PS-wire / host-input / readback /
        checkpoint / rollback-replay buckets that sum to the recorded
        wall time by construction. None when tracing is off (the
        decomposition needs the span tree); under ``ADT_TRACE=sampled``
        (or after ring-buffer drops) the report is flagged
        ``approximate`` — bucket *proportions* hold, absolute seconds
        scale with the stride."""
        if not tel.tracing_enabled():
            return None
        from autodist_tpu.telemetry import goodput as goodput_lib
        report = goodput_lib.build_report()
        if report.wall_s <= 0:
            return None
        return report

    def _check_ps_owner_health(self):
        """Fail LOUDLY when an async-PS owner apply loop of this process
        is dead (transport budget exhausted / thread crashed). Before
        this check the failure mode was a silent stall: the daemon thread
        died, queues backed up, and training "ran" forever applying
        nothing. Checked every step — it is two attribute reads when
        healthy."""
        store = getattr(self._dstep, "ps_store", None)
        if store is None or not getattr(store, "serving", False):
            return
        bad = store.owner_health_errors()
        if bad:
            raise RuntimeError(
                "async PS owner apply loop(s) dead — training cannot "
                "apply gradients: %s"
                % "; ".join("%s: %s" % (h, e) for h, e in bad))

    def _maybe_heartbeat(self):
        """Time-based liveness beat for async multi-process jobs. A failed
        beat RECONNECTS at the next due time instead of latching off: a
        worker that silently stopped heartbeating would age into a false
        death at the chief's watchdog — the one thing this beat exists to
        prevent.

        Deliberately STEP-DRIVEN, not a background thread: the beat means
        "this worker made training progress recently", which is the signal
        a deadlock detector needs — a daemon thread would keep beating
        while the main thread is wedged in a lock or syscall, masking
        exactly the hang being watched for. The flip side: legitimate
        non-stepping phases (long evals, slow data) read as silence, so
        ``ADT_HEARTBEAT_TIMEOUT_S`` must exceed the job's worst honest
        inter-step gap."""
        if not self._hb_enabled:
            return
        now = time.monotonic()
        if now - self._last_hb <= self._heartbeat_every_s:
            return
        if self._async_hb is None:
            self._async_hb = self._connect_coordination(
                "async liveness heartbeats (reconnect)")
            if self._async_hb is None:
                return  # retry at the next due beat
        try:
            self._async_hb.heartbeat(const.ENV.ADT_WORKER.val or "chief")
            self._last_hb = now
        except OSError as e:
            logging.warning("async heartbeat failed (%s); reconnecting at "
                            "the next beat", e)
            try:
                self._async_hb.close()
            except OSError:
                pass
            self._async_hb = None

    def _maybe_check_mirrors(self):
        """Sync multi-process PS keeps every process's host mirror
        bit-identical by determinism, not by serving; every
        ``ADT_PS_MIRROR_CHECK_EVERY`` steps compare an md5 digest of the
        mirrors across processes via the coordination service and fail
        fast on divergence (heterogeneous host XLA codegen would
        otherwise silently fork the replicas)."""
        every = const.ENV.ADT_PS_MIRROR_CHECK_EVERY.val
        store = getattr(self._dstep, "ps_store", None)
        if (every <= 0 or store is None or store.serving
                or const.ENV.ADT_NUM_PROCESSES.val < 2
                or self._step_count % every != 0
                or self._mirror_coord is False):  # disabled after a timeout
            return
        # a DEDICATED client: self._coord doubles as the "staleness pacing
        # on" flag in run(), which must stay off unless staleness > 0
        if self._mirror_coord is None:
            self._mirror_coord = self._connect_coordination("mirror check")
            if self._mirror_coord is None:
                self._mirror_coord = False
                return
        # the pipelined push for this step must land before the digest, or
        # processes would hash different apply versions (false divergence)
        self._dstep.flush_ps()
        digest = store.mirror_digest()
        worker = const.ENV.ADT_WORKER.val or "chief"
        # keys are scoped by strategy id (unique per run — a long-lived
        # service may retain a previous run's digests) with ONE key per
        # worker, overwritten each check (bounded KV growth); all
        # processes check at the same step multiples, and sync PS steps
        # are collective-lockstep, so the steps line up
        prefix = "mirror/%s" % getattr(self._dstep.strategy, "id", "run")
        self._mirror_coord.put("%s/%s" % (prefix, worker),
                               "%d:%s" % (self._step_count, digest))
        if worker == "chief":
            return  # workers compare against the chief's copy
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            val = self._mirror_coord.get("%s/chief" % prefix)
            if val is not None:
                chief_step, chief_digest = val.split(":", 1)
                if int(chief_step) >= self._step_count:
                    if (int(chief_step) == self._step_count
                            and chief_digest != digest):
                        raise RuntimeError(
                            "PS mirror divergence at step %d: %s has %s, "
                            "chief has %s" % (self._step_count, worker,
                                              digest, chief_digest))
                    return  # matched, or chief raced past — next check aligns
            time.sleep(0.01)
        # never saw a chief digest for this step: warn once and stop
        # checking rather than stalling 30s at every future check step
        logging.warning("mirror check: chief digest for step %d never "
                        "appeared; disabling further checks",
                        self._step_count)
        self._mirror_coord.close()
        self._mirror_coord = False

    def close(self):
        """Release everything the runner opened: coordination-service
        clients (pacing + liveness + mirror check, with a clean GOODBYE
        deregister so a finished worker is never counted dead) and the
        host-PS store's serving threads/sockets. Idempotent."""
        worker = const.ENV.ADT_WORKER.val or "chief"
        self._hb_enabled = False
        guard = getattr(self, "_preempt", None)
        if guard is not None:
            guard.close()
        if getattr(self, "_atexit_cb", None) is not None:
            import atexit
            try:
                atexit.unregister(self._atexit_cb)
            except Exception:  # noqa: BLE001 — unregister is best-effort
                pass
            self._atexit_cb = None
        for attr, say_goodbye in (("_coord", True), ("_async_hb", True),
                                  ("_mirror_coord", False)):
            client = getattr(self, attr, None)
            if client not in (None, False):
                try:
                    if say_goodbye:
                        client.goodbye(worker)
                except OSError:
                    pass
                finally:  # a failed goodbye must not leak the socket
                    try:
                        client.close()
                    except OSError:
                        pass
            setattr(self, attr, None)
        store = getattr(self._dstep, "ps_store", None)
        if store is not None:
            # land the in-flight pipelined push and stop its executor
            # threads BEFORE tearing the store down — a background push
            # against a closed store would fail into a never-awaited
            # Future, silently losing the last step's gradient
            try:
                self._dstep.close_ps()
            except Exception as e:  # noqa: BLE001 — close stays idempotent
                logging.warning("PS pipeline close failed: %s", e)
            store.close()

    def gather_params(self):
        return self._dstep.gather_params(self.state)

    # --------------------------------------------------- fit/evaluate facade

    def fit(self, batches, steps: Optional[int] = None,
            callbacks: Optional[list] = None, save_every: int = 0,
            saver=None, fuse_steps: int = 1, metrics_every: int = 1) -> list:
        """Train over an iterable of host batches (the reference's Keras
        ``model.fit`` path, which its patch routed into the distributed
        session — reference ``patch.py:96-197``). ``steps`` bounds infinite
        iterables (e.g. RecordFileDataset) without consuming a batch past
        the bound; ``callbacks`` are called as ``cb(step_index, metrics)``
        after every step. ``save_every=N`` checkpoints every N steps (and
        once at the end) through ``saver`` — default an async
        :class:`~autodist_tpu.checkpoint.saver.Saver` on ``ADT_CKPT_DIR``,
        which is exactly what sync-elastic recovery resumes from. Returns
        per-step metrics.

        ``fuse_steps=k > 1`` drives the FUSED engine: k consecutive
        batches are stacked into one ``[k, ...]`` feed (or taken
        pre-stacked from a ``DevicePrefetcher(..., stack=k)``) and run as
        one donated jitted superstep — no host re-entry between the k
        optimizer applies. ``metrics_every=n`` pays the device→host
        metrics readback only every n supersteps; between boundaries
        ZERO device→host copies happen. History entries stay
        per-microstep (one dict per batch), so examples/s math and parity
        with the per-step loop are unchanged; callbacks also fire
        per-microstep but only AT readback boundaries (their values are
        exact, their timing is deferred — a monitor that must run every
        step needs ``fuse_steps=1, metrics_every=1``). ``save_every``
        rounds UP to the next superstep boundary (a checkpoint cannot
        split a fused program). When ``fit`` does the stacking (a plain
        host-batch iterable), a trailing group smaller than k falls back
        to per-step execution, so any batch count is trained exactly;
        PRE-stacked sources cannot be split — ``DevicePrefetcher(stack=k)``
        drops a short tail (with a warning) and a ``steps`` bound that is
        not a multiple of k stops at the last whole superstep."""
        # one long span bracketing the whole fit window: the per-dispatch
        # spans nest inside it, so a trace shows the training phase as a
        # single labeled interval with its knobs as args
        with tel.span("runner.fit", "runner", fuse_steps=fuse_steps,
                      metrics_every=metrics_every, save_every=save_every):
            return self._fit(batches, steps, callbacks, save_every, saver,
                             fuse_steps, metrics_every)

    def _fit(self, batches, steps, callbacks, save_every, saver,
             fuse_steps, metrics_every) -> list:
        # the body of fit() — the public contract lives on fit's
        # docstring; split out only so the whole window runs inside one
        # "runner.fit" span
        src_k = getattr(batches, "stack_k", 1)
        if src_k != 1 and src_k != max(1, fuse_steps):
            # a stacked source feeding the wrong k would not fail loudly:
            # remap would split the [k] scan dim over replicas (or re-stack
            # an already-stacked feed) and broadcast-tolerant models would
            # silently train on mis-shaped data
            raise ValueError(
                "fit(fuse_steps=%d) fed a source pre-stacked with stack=%d"
                " — the stacks must match (DevicePrefetcher(stack=k) pairs"
                " with fit(fuse_steps=k))" % (fuse_steps, src_k))
        if save_every > 0 and saver is None:
            from autodist_tpu.checkpoint.saver import Saver
            saver = Saver(directory=const.ENV.ADT_CKPT_DIR.val,
                          async_save=True)
        if self._sentinel is not None and saver is not None:
            # rollback restores from where fit checkpoints
            self._sentinel.attach_saver(saver)
        if saver is not None:
            # the rescue checkpoint commits where fit checkpoints too
            self._preempt.attach_saver(saver)
        if fuse_steps > 1 or metrics_every > 1:
            return self._fit_pipelined(batches, steps, callbacks, save_every,
                                       saver, max(1, fuse_steps),
                                       max(1, metrics_every))
        history = []
        bounded = batches if steps is None else itertools.islice(batches, steps)
        try:
            for i, batch in enumerate(bounded):
                metrics = self.run(batch)
                history.append(metrics)
                for cb in (callbacks or ()):
                    cb(i, metrics)
                if save_every > 0 and (i + 1) % save_every == 0:
                    saver.save(self)
            # the LAST step's verdict may have pended a rollback; act
            # before the trailing save so a hard-fail surfaces from fit
            self._maybe_sentinel_act()
            if save_every > 0 and history and len(history) % save_every != 0:
                saver.save(self)  # final partial window
        finally:
            # even on an exception path, a failed async checkpoint write
            # must surface — never look like a success
            if saver is not None:
                saver.wait()
        return history

    def _fit_pipelined(self, batches, steps, callbacks, save_every, saver,
                       k: int, metrics_every: int) -> list:
        """The fused / async steady-state driver behind
        ``fit(fuse_steps=k, metrics_every=n)``: supersteps dispatch with
        ``sync=False`` and their :class:`MetricsHandle`\\ s accumulate
        device-side; one readback per n supersteps (and one at the end)
        materializes them into the per-microstep history."""
        history: list = []
        pending: list = []  # un-materialized MetricsHandles, in step order

        def materialize():
            # pop each handle BEFORE firing its callbacks: a callback that
            # raises must not leave the handle queued, or the finally-path
            # materialize would re-run its side effects (double
            # checkpoint/log writes) on the way out
            while pending:
                handle = pending.pop(0)
                for m in handle.unstack():
                    idx = len(history)
                    history.append(m)
                    for cb in (callbacks or ()):
                        cb(idx, m)

        # a DevicePrefetcher in matching stack mode yields pre-stacked,
        # pre-placed [k, ...] feeds — consume them whole; any other source
        # yields plain batches that are grouped and stacked here
        pre_stacked = k > 1 and getattr(batches, "stack_k", 1) == k
        it = iter(batches)
        micro_done, last_save, supersteps = 0, 0, 0
        try:
            while steps is None or micro_done < steps:
                if pre_stacked:
                    if steps is not None and micro_done + k > steps:
                        logging.warning(
                            "fit: steps=%d is not a multiple of "
                            "fuse_steps=%d on a pre-stacked source; "
                            "stopping at %d microsteps", steps, k, micro_done)
                        break
                    try:
                        stacked = next(it)
                    except StopIteration:
                        break
                    handles = [self.run_superstep(stacked, sync=False)]
                else:
                    group = []
                    while len(group) < k and (steps is None
                                              or micro_done + len(group)
                                              < steps):
                        try:
                            group.append(next(it))
                        except StopIteration:
                            break
                    if not group:
                        break
                    if len(group) == k and k > 1:
                        from autodist_tpu.data.prefetch import stack_batches
                        handles = [self.run_superstep(stack_batches(group),
                                                      sync=False)]
                    else:
                        # trailing partial group: per-step, still async
                        handles = [self.run(b, sync=False) for b in group]
                pending.extend(handles)
                micro_done += sum(h.microsteps for h in handles)
                supersteps += 1
                if supersteps % metrics_every == 0:
                    materialize()
                    self._maybe_sentinel_act()
                if save_every > 0 and micro_done - last_save >= save_every:
                    # superstep-boundary rounding: the save covers every
                    # microstep dispatched so far (saver reads through
                    # flush_ps, which lands the fused PS carry)
                    saver.save(self)
                    last_save = micro_done
            materialize()
            self._maybe_sentinel_act()
            if save_every > 0 and micro_done > last_save:
                saver.save(self)  # final partial window
        finally:
            # NO materialize here: on an exception path the history is
            # lost with the raise, and firing user callbacks after one of
            # them (or the step) aborted would run side effects the
            # caller believes cancelled. Un-materialized handles just
            # drop their device buffers.
            del pending[:]
            # land the fused PS carry: after fit() returns, the host store
            # is authoritative again for checkpoints/eval/inspection
            self._dstep.flush_ps()
            if saver is not None:
                saver.wait()
        return history

    def evaluate(self, batches, steps: Optional[int] = None) -> dict:
        """Example-weighted mean of the SCALAR metrics over an iterable of
        host batches, without updating parameters (the reference's
        ``model.evaluate``). Runs the forward-only compiled program — no
        grads, no optimizer, no gradient collectives. Each batch's scalars
        are weighted by its example count (the leading dim of its first
        array leaf), so a ragged final batch contributes proportionally
        instead of skewing a mean-of-means; batches with no array leaves
        weight 1. Non-scalar metrics are skipped (warned once); aggregate
        those from per-step ``run`` output instead."""
        import numpy as np
        if self.state is None:
            raise RuntimeError("Runner.evaluate before init()")
        totals, weight, skipped = {}, 0.0, set()
        # ONE host-PS pull for the whole eval loop: no pushes happen
        # between eval batches, so the values cannot change — a consistent
        # snapshot, and per-batch re-pulls would be pure PCIe waste.
        # pull_ps is the public snapshot API; it also lands a dirty fused
        # superstep carry first, so eval-mid-fit sees every microstep.
        ps_vals = self._dstep.pull_ps()
        bounded = batches if steps is None else itertools.islice(batches, steps)
        for batch in bounded:
            n = self._batch_examples(batch)
            sharded = self._remapper.remap_feed(batch)
            metrics = self._dstep.evaluate(self.state, sharded,
                                           ps_vals=ps_vals)
            host = self._remapper.remap_fetch(metrics)
            for k, v in host.items():
                if np.ndim(v) == 0:
                    totals[k] = totals.get(k, 0.0) + float(v) * n
                elif k not in skipped:
                    skipped.add(k)
                    logging.warning("evaluate: skipping non-scalar metric "
                                    "%r (shape %s)", k, np.shape(v))
            weight += n
        if weight == 0.0:
            return {}
        return {k: v / weight for k, v in totals.items()}

    @staticmethod
    def _batch_examples(batch) -> int:
        """Leading-dim example count of one batch (1 if no array leaf —
        a weightless batch still counts once in the mean)."""
        for leaf in jax.tree_util.tree_leaves(batch):
            shape = np.shape(leaf)
            if len(shape) >= 1:
                return int(shape[0])
        return 1

    def predict(self, batch, serve_fn, ps_vals=None) -> dict:
        """One-shot forward-only inference on a host batch: run the
        compiled fetch program (``DistributedStep.predict_program``) and
        return ``serve_fn(params, batch)``'s outputs on host, under the
        user's original names (via the Remapper — sharded per-example
        outputs reassemble into the global batch order).

        This is the ad-hoc single call; sustained traffic wants the
        serving engine (``autodist_tpu/serving/``), which adds bucketed
        batch shapes (zero steady-state recompiles), request
        micro-batching, per-request latency accounting, and graceful
        degradation. ``ps_vals`` lets a caller loop reuse one host-PS
        snapshot across calls (as :meth:`evaluate` does); the program
        runs un-donated here because the caller may hold references to
        the placed batch."""
        if self.state is None:
            raise RuntimeError("Runner.predict before init()")
        program = self._dstep.predict_program(serve_fn, donate_batch=False,
                                              example_batch=batch)
        if ps_vals is None:
            ps_vals = self._dstep.pull_ps()
        sharded = self._remapper.remap_feed(batch)
        return self._remapper.remap_fetch(
            program(self.state, ps_vals, sharded))


class WrappedSession:
    """Thin session facade over Runner for reference-style ergonomics
    (``session.run(feed)`` loops)."""

    def __init__(self, runner: Runner):
        self._runner = runner

    def run(self, feed_dict=None, **kwargs):
        batch = feed_dict if feed_dict is not None else kwargs
        return self._runner.run(batch)

    def fit(self, batches, steps=None, callbacks=None, save_every=0,
            saver=None, fuse_steps=1, metrics_every=1):
        return self._runner.fit(batches, steps=steps, callbacks=callbacks,
                                save_every=save_every, saver=saver,
                                fuse_steps=fuse_steps,
                                metrics_every=metrics_every)

    def evaluate(self, batches, steps=None):
        return self._runner.evaluate(batches, steps=steps)

    def predict(self, feed_dict, serve_fn, ps_vals=None):
        """Forward-only fetches for one fed batch (``Runner.predict``)."""
        return self._runner.predict(feed_dict, serve_fn, ps_vals=ps_vals)

    @property
    def state(self):
        return self._runner.state

    def gather_params(self):
        return self._runner.gather_params()
