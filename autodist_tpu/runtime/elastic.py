"""Elastic membership plane: epoch-fenced rosters for in-run shrink/grow.

AutoDist's supervision ladder so far: fail-fast (the reference), per-worker
relaunch for async PS (PR 1, ``ADT_ELASTIC``), and whole-job
checkpoint-restore re-exec for sync jobs (PR 8, ``ADT_ELASTIC_SYNC``). This
module adds the missing half — **live** reconfiguration: when a sync worker
dies, the survivors re-form a smaller ``jax.distributed`` process set
in-run and keep training (shrink-to-survivors), then re-absorb a
relaunched or hot-spare worker the same way (grow-on-join). No re-exec, no
disk round-trip when every shard of the training state has a live replica
on a survivor.

The safety core is the **cluster epoch**: a monotonically increasing
integer the chief publishes to the coordination service together with the
membership roster. Every epoch bump is a membership change; every process
carries the epoch it joined under. Fencing then closes the classic
split-brain hole of failure detectors: a worker that was *declared* dead
but is merely slow (GC pause, network partition, SIGSTOP) wakes up holding
a stale epoch — and every mutating control-plane or PS-wire write it
attempts (gradient push, value publish, barrier arrival, checkpoint
commit, KV liveness marks) is rejected with the typed :class:`FencedOut`
before it can corrupt state its replacement now owns. The check is
one KV read against the service's authoritative ``elastic/epoch``:

- same epoch (or no elastic plane installed) → write proceeds;
- newer epoch, but this worker is **in** the new roster → write proceeds
  (it is a lagging survivor mid-superstep; it will reconfigure at its
  next readback boundary);
- newer epoch and this worker is **not** in the roster → ``FencedOut``
  (it is a zombie: evicted, possibly replaced).

Protocol keys (all on the native coordination service):

====================================  =======================================
``elastic/epoch``                      authoritative epoch (int as str)
``elastic/roster``                     comma-joined member addresses for it
``elastic/reconf/<epoch>``             the survivors' reconfiguration barrier
``elastic/ack/<epoch>/<worker>``       per-survivor "reconfigured" ack
``elastic/join/<worker>``              a joiner's admission announcement
====================================  =======================================

The roster is written BEFORE the epoch: readers key on the epoch, so the
pair is consistent the moment the epoch lands (the service serializes
requests on one thread).
"""
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu import const
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging

EPOCH_KEY = "elastic/epoch"
ROSTER_KEY = "elastic/roster"


# --------------------------------------------------------------- typed errors


class ElasticConfigError(ValueError):
    """An elastic knob holds a value that cannot mean anything.

    Raised at bring-up instead of silently disabling elasticity: a typo'd
    ``ADT_ELASTIC=-1`` (or ``ADT_ELASTIC=yes``) that quietly parsed to
    "off" would surface months later as a job that fail-fasts when its
    operator believed it was elastic."""

    def __init__(self, knob: str, raw: str, why: str):
        self.knob = knob
        self.raw = raw
        super().__init__(
            "invalid %s=%r: %s (unset it, or set a valid value)"
            % (knob, raw, why))


class FencedOut(Exception):
    """A stale-epoch write was rejected by the membership fence.

    Deliberately NOT an ``OSError``/``RuntimeError`` subclass: the
    transport-resilience handlers (retry loops, best-effort mark writers)
    swallow those, and a fenced zombie must stop — its identity has been
    taken over, and every further write risks corrupting the successor's
    state. The one correct reaction is to exit (or re-join as a fresh
    member via the admission protocol)."""

    def __init__(self, op: str, mine: int, current: int,
                 worker: str = "", roster: Sequence[str] = ()):
        self.op = op
        self.my_epoch = mine
        self.current_epoch = current
        self.worker = worker
        self.roster = list(roster)
        super().__init__(
            "%s fenced out: this process carries cluster epoch %d but the "
            "membership plane is at epoch %d and its roster %s no longer "
            "includes %r — a newer incarnation owns this identity; refusing "
            "the write" % (op, mine, current, self.roster, worker))


# ------------------------------------------------------------ knob validation

_BOOL_RAW = ("", "0", "1", "False", "false", "True", "true")


def validate_elastic_knobs() -> Tuple[int, bool, bool]:
    """Parse the elastic bring-up knobs LOUDLY; returns
    ``(budget, sync_elastic, inrun)``.

    ``const.ENV``'s generic parsers are permissive by design (any unknown
    string is a truthy bool; ``int()`` raises a bare ``ValueError`` with no
    knob name). Elasticity is a safety feature, so its knobs get strict
    validation with a typed error naming the knob."""
    import os
    raw = os.environ.get(const.ENV.ADT_ELASTIC.name_str)
    if raw is None:
        budget = 0
    else:
        try:
            budget = int(raw)
        except ValueError:
            raise ElasticConfigError(
                const.ENV.ADT_ELASTIC.name_str, raw,
                "must be an integer restart budget (0 disables elasticity)"
            ) from None
        if budget < 0:
            raise ElasticConfigError(
                const.ENV.ADT_ELASTIC.name_str, raw,
                "a negative restart budget is meaningless")
    out = [budget]
    for env in (const.ENV.ADT_ELASTIC_SYNC, const.ENV.ADT_ELASTIC_INRUN):
        raw = os.environ.get(env.name_str)
        if raw is not None and raw not in _BOOL_RAW:
            raise ElasticConfigError(
                env.name_str, raw,
                "must be one of %s" % (_BOOL_RAW,))
        out.append(env.val)
    if out[2] and not out[1]:
        raise ElasticConfigError(
            const.ENV.ADT_ELASTIC_INRUN.name_str, "1",
            "in-run reconfiguration is the sync-elastic upgrade path and "
            "needs ADT_ELASTIC_SYNC=1 at bring-up")
    if out[2] and out[0] <= 0:
        raise ElasticConfigError(
            const.ENV.ADT_ELASTIC_INRUN.name_str, "1",
            "needs a positive ADT_ELASTIC budget (each in-run "
            "reconfiguration spends one restart)")
    return out[0], out[1], out[2]


# ------------------------------------------------------------- epoch protocol


def read_epoch(client) -> Optional[Tuple[int, List[str]]]:
    """The service's ``(epoch, roster)``, or None when no epoch was ever
    published (non-elastic job / service restarted)."""
    raw = client.get(EPOCH_KEY)
    if not raw:
        return None
    try:
        epoch = int(raw)
    except ValueError:
        return None
    roster_raw = client.get(ROSTER_KEY) or ""
    return epoch, [a for a in roster_raw.split(",") if a]


def publish_epoch(client, epoch: int, roster: Sequence[str]):
    """Chief-side: commit a membership change. Roster first, then the
    epoch (the commit point readers key on). Refuses to move backwards —
    a re-published lower epoch would un-fence every zombie at once."""
    cur = read_epoch(client)
    if cur is not None and epoch <= cur[0]:
        raise ValueError(
            "elastic epoch must increase monotonically: refusing to "
            "publish epoch %d over current %d" % (epoch, cur[0]))
    client.put(ROSTER_KEY, ",".join(roster))
    client.put(EPOCH_KEY, str(epoch))
    tel.gauge_set("elastic.epoch", float(epoch))
    tel.instant("elastic.epoch_published", "elastic", epoch=epoch,
                world=len(roster))
    logging.warning("elastic: published cluster epoch %d (roster: %s)",
                    epoch, ",".join(roster))


def announce_join(client, worker: str):
    """A relaunched/hot-spare worker asks for admission; the chief's
    watchdog answers with a grown-roster epoch at the next boundary."""
    client.put("elastic/join/%s" % worker, repr(time.time()))


def pending_join(client, worker: str,
                 freshness_s: float = 600.0) -> bool:
    """True while ``worker`` holds a fresh admission announcement."""
    raw = client.get("elastic/join/%s" % worker)
    if not raw:
        return False
    try:
        ts = float(raw)
    except ValueError:
        return False
    return ts > 0 and time.time() - ts < freshness_s


def clear_join(client, worker: str):
    client.put("elastic/join/%s" % worker, "0")


def admit_worker(client, worker: str) -> int:
    """Chief-side grow-on-join admission as one move: publish the next
    epoch with ``worker`` appended to the current roster and consume its
    join announcement (if any). This is the actuator the chief's
    watchdog and the serving autoscaler share — the admitted worker's
    Runner adopts the grown mesh at its next epoch poll. Returns the new
    epoch. No-op (returns the current epoch) when the worker is already
    a member. Raises :class:`RuntimeError` when no epoch was ever
    published — there is no roster to grow."""
    info = read_epoch(client)
    if info is None:
        raise RuntimeError(
            "admit_worker(%r): no membership epoch published — "
            "publish_epoch a launch roster first" % worker)
    epoch, roster = info
    if worker in roster:
        return epoch
    publish_epoch(client, epoch + 1, list(roster) + [worker])
    clear_join(client, worker)
    return epoch + 1


def gc_worker_marks(client, worker: str):
    """Watchdog hygiene: scrub every liveness record a dead incarnation of
    ``worker`` may have left — its heartbeat (GOODBYE), its ``compiling``
    grace mark and its ``straggler`` slow-but-alive mark (tombstoned to
    "0", which both readers treat as cleared). Without this, a dead
    incarnation's fresh-looking marks could satisfy — or poison — the
    watchdog's freshness checks against the NEXT incarnation across an
    epoch change (a worker flagged straggling in epoch N must not carry
    the flag into its epoch N+1 self)."""
    for op in (lambda: client.goodbye(worker),
               lambda: client.put("compiling/%s" % worker, "0"),
               lambda: client.put("straggler/%s" % worker, "0")):
        try:
            op()
        except (OSError, RuntimeError):
            pass  # hygiene is best-effort; marks also age out


# ---------------------------------------------------------------- membership


class Membership:
    """One process's view of the elastic membership plane.

    Holds the worker identity, the epoch this process currently operates
    under, and that epoch's roster; owns a dedicated (raw, auto-reconnect)
    coordination client so fence checks never share a socket with — or
    deadlock against — the operation being fenced."""

    def __init__(self, worker: str, epoch: int, roster: Sequence[str],
                 client_factory: Optional[Callable] = None,
                 fence_cache_s: float = 0.05):
        self.worker = worker
        self.epoch = epoch
        self.roster = list(roster)
        self._factory = client_factory or self._default_factory
        self._client = None
        self._lock = threading.Lock()
        # read-side cache: a fence check inside the window reuses the last
        # (epoch, roster) instead of issuing two more KV reads — without
        # it, EVERY mutating control-plane op (and each per-step PS push)
        # would pay 2 extra serialized RPCs. The 50 ms default is far
        # inside the protocol's inherent race window (death detection
        # itself takes a heartbeat/process-watch interval), so it weakens
        # nothing: a write that slips through within 50 ms of the epoch
        # bump was indistinguishable from one already in flight. 0 makes
        # every check exact (tests).
        self._fence_cache_s = fence_cache_s
        self._cached: Optional[Tuple[int, List[str]]] = None
        self._cached_at = float("-inf")
        self.joined_late = False  # admitted via grow-on-join (not launch)
        # wall-clock deadline of an ANNOUNCED departure of THIS worker
        # (runtime/preemption.py): until it passes, the fence yields —
        # the planned-shrink epoch is published while the leaver still
        # runs its final lockstep boundary (rescue checkpoint, flush),
        # and fencing those writes would strand its peers mid-collective
        self._departure_until = 0.0

    @staticmethod
    def _default_factory():
        from autodist_tpu.runtime.coordination import CoordinationClient
        host = (const.ENV.ADT_COORDINATOR_ADDR.val.split(":")[0]
                or "127.0.0.1")
        return CoordinationClient(host, const.ENV.ADT_COORDSVC_PORT.val,
                                  timeout=const.ENV.ADT_RPC_TIMEOUT_S.val
                                  or None)

    def _with_client(self, fn):
        with self._lock:
            if self._client is None:
                self._client = self._factory()
            try:
                return fn(self._client)
            except OSError:
                try:
                    self._client.close()
                except OSError:
                    pass
                self._client = None
                raise

    def peek(self) -> Optional[Tuple[int, List[str]]]:
        """The service's current (epoch, roster); None when unreachable
        or never published."""
        now = time.monotonic()
        if (self._cached is not None
                and now - self._cached_at < self._fence_cache_s):
            return self._cached
        try:
            info = self._with_client(read_epoch)
        except OSError:
            return None
        if info is not None:
            self._cached, self._cached_at = info, now
        return info

    def expect_departure(self, deadline: float):
        """Announced planned departure of THIS worker
        (``runtime/preemption.py``): keep the fence open for it until
        ``deadline`` even after an epoch excludes it — the leaver
        participates ALIVE in its final boundary (rescue checkpoint,
        flush, left stamp) by design, and its peers are in collectives
        with it. Past the deadline the platform's SIGKILL has fired and
        zombie semantics resume: a late incarnation is fenced again."""
        self._departure_until = max(self._departure_until, float(deadline))

    def fence(self, op: str):
        """Raise :class:`FencedOut` when this process's epoch is stale AND
        the current roster no longer includes it (see module docstring for
        why lagging survivors pass — and :meth:`expect_departure` for why
        an announced leaver passes until its deadline). Service
        unreachable → the write proceeds: the fence guards against
        zombies, and must not turn a control-plane blip into a training
        outage (the resilient client and degradation windows own that
        failure class)."""
        info = self.peek()
        if info is None:
            return
        epoch, roster = info
        if time.time() < self._departure_until:
            return  # announced leaver finishing its final boundary
        if epoch > self.epoch and self.worker not in roster:
            tel.counter_add("elastic.fenced_writes")
            tel.instant("elastic.fenced_write", "elastic", op=op,
                        mine=self.epoch, current=epoch, worker=self.worker)
            from autodist_tpu.telemetry import blackbox
            blackbox.record("elastic.fenced_write", op=op, mine=self.epoch,
                            current=epoch, worker=self.worker)
            raise FencedOut(op, self.epoch, epoch, self.worker, roster)

    def adopt(self, epoch: int, roster: Sequence[str]):
        """This process finished reconfiguring under ``epoch``."""
        self.epoch = epoch
        self.roster = list(roster)
        self._cached = (epoch, list(roster))
        self._cached_at = time.monotonic()
        tel.gauge_set("elastic.epoch", float(epoch))

    def barrier_reconf(self, epoch: int, num_workers: int):
        """The survivors' reconfiguration barrier — superstep-aligned
        (every caller sits at a readback boundary), so no process is
        stranded mid-collective when the old process set is torn down.
        Blocking by design (members arrive up to a superstep apart), so
        the per-RPC deadline is lifted for the call."""
        def call(c):
            c.set_rpc_timeout(None)
            try:
                return c.barrier("elastic/reconf/%d" % epoch, num_workers)
            finally:
                try:
                    c.set_rpc_timeout(const.ENV.ADT_RPC_TIMEOUT_S.val
                                      or None)
                except OSError:
                    pass
        self._with_client(call)

    def ack(self, epoch: int):
        """Record that this worker completed the ``epoch`` reconfigure
        (the chief's escalation timer waits on these)."""
        self._with_client(
            lambda c: c.put("elastic/ack/%d/%s" % (epoch, self.worker), "1"))

    def close(self):
        with self._lock:
            if self._client is not None:
                try:
                    self._client.close()
                except OSError:
                    pass
                self._client = None


_current: Optional[Membership] = None


def install(membership: Membership) -> Membership:
    """Install the process-ambient membership (one per process — the
    fence hooks in the resilience client, PS wire and savers read it)."""
    global _current
    _current = membership
    tel.gauge_set("elastic.epoch", float(membership.epoch))
    return membership


def current() -> Optional[Membership]:
    return _current


def clear():
    global _current
    if _current is not None:
        _current.close()
    _current = None


def maybe_fence(op: str):
    """Fence hook for write paths: no-op (one global read) unless a
    membership plane is installed in this process."""
    m = _current
    if m is not None:
        m.fence(op)


# -------------------------------------------------- process-set rejoin helper


def roster_layout(roster: Sequence[str],
                  chief: Optional[str] = None) -> List[str]:
    """Deterministic process layout for a roster: chief first, the rest
    sorted — every member computes the same ids with no extra round trip
    (the same determinism ``Cluster`` gets from sorted addresses)."""
    members = list(dict.fromkeys(roster))
    if chief is None:
        chief = members[0] if members else ""
    if chief not in members:
        raise ValueError("roster %s does not contain chief %r"
                         % (members, chief))
    return [chief] + sorted(a for a in members if a != chief)


def epoch_coordinator_address(epoch: int) -> str:
    """The jax.distributed coordinator address for ``epoch``. Epoch 1
    (the launch epoch) IS the configured address — the initial bring-up
    path stays byte-identical; each later epoch binds a fresh port
    (base − ((epoch−1) mod 89)): the previous process set's runtime may
    still be draining its socket, and every member derives the same
    offset from the shared epoch."""
    addr = const.ENV.ADT_COORDINATOR_ADDR.val
    if addr and ":" in addr:
        host, port = addr.rsplit(":", 1)
        base = int(port)
    else:
        host, base = "127.0.0.1", const.DEFAULT_COORDINATOR_PORT
    return "%s:%d" % (host, base - ((epoch - 1) % 89))


def rejoin_process_set(roster: Sequence[str], epoch: int,
                       chief: Optional[str] = None):
    """Tear down this process's jax.distributed membership and re-join as
    the ``epoch`` process set (the in-run half of what PR 8's whole-job
    re-exec achieved by replacing the process image). Call ONLY from a
    readback boundary after the reconfiguration barrier — live device
    buffers of the old mesh are invalid afterwards."""
    from autodist_tpu.runtime import server_starter
    layout = roster_layout(roster, chief)
    me = const.ENV.ADT_WORKER.val or layout[0]
    if me not in layout:
        raise FencedOut("rejoin", -1, epoch, me, layout)
    import os
    os.environ[const.ENV.ADT_NUM_PROCESSES.name_str] = str(len(layout))
    os.environ[const.ENV.ADT_PROCESS_ID.name_str] = str(layout.index(me))
    server_starter.reinit_distributed(
        coordinator_address=epoch_coordinator_address(epoch),
        num_processes=len(layout), process_id=layout.index(me))


# ------------------------------------------------- worker-side admission wait


def wait_for_admission(worker: str, timeout_s: float = 600.0
                       ) -> Optional[Tuple[int, List[str]]]:
    """A relaunched/hot-spare worker's bring-up: announce a join and poll
    until an epoch's roster includes us, then return ``(epoch, roster)``
    (the caller joins that epoch's jax.distributed set). Returns None when
    no epoch was ever published (first launch — join from the env instead).
    """
    from autodist_tpu.runtime.coordination import CoordinationClient
    host = (const.ENV.ADT_COORDINATOR_ADDR.val.split(":")[0]
            or "127.0.0.1")
    try:
        client = CoordinationClient(host, const.ENV.ADT_COORDSVC_PORT.val)
    except OSError:
        return None
    try:
        info = read_epoch(client)
        if info is None:
            return None  # pre-epoch bring-up: the normal launch path
        epoch, roster = info
        if worker in roster:
            return epoch, roster  # already admitted (fast relaunch)
        announce_join(client, worker)
        logging.warning("elastic: %s announced itself for admission "
                        "(current epoch %d)", worker, epoch)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            info = read_epoch(client)
            if info is not None and worker in info[1]:
                logging.warning("elastic: %s admitted at epoch %d",
                                worker, info[0])
                return info
            time.sleep(0.2)
        raise TimeoutError(
            "elastic admission: %s was not admitted within %.0fs"
            % (worker, timeout_s))
    finally:
        try:
            client.close()
        except OSError:
            pass


def broadcast_state(snapshot: Optional[dict] = None) -> dict:
    """Collective state handoff after a GROW: process 0 (the chief, a
    survivor) broadcasts its host snapshot to the whole new process set
    so the joiner — which has no state — adopts the run's truth. A plain
    byte broadcast for now; the arXiv 2112.01075 redistribution
    collectives are the scale upgrade (ship only the shards each member
    needs) once state stops fitting one host."""
    import pickle
    import jax
    from autodist_tpu.runtime import server_starter
    payload = (pickle.dumps(snapshot)
               if jax.process_index() == 0 else None)
    return pickle.loads(server_starter.broadcast_bytes(payload))


# ------------------------------------------- in-memory state snapshot/adopt


def _local_full_value(arr) -> Optional[np.ndarray]:
    """Assemble the FULL value of a (possibly global) jax.Array from this
    process's addressable shards alone — no collectives (the old process
    set may already be missing a member). None when the local shards do
    not cover the array (cross-process sharded state: the caller falls
    back to the last-good checkpoint re-shard)."""
    import jax
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        return np.asarray(jax.device_get(arr))
    shape = tuple(arr.shape)
    out = np.empty(shape, dtype=arr.dtype) if shape else None
    covered = np.zeros(shape, dtype=bool) if shape else False
    for s in shards:
        data = np.asarray(s.data)
        if not shape:
            return data  # scalar: any shard is the value
        out[s.index] = data
        covered[s.index] = True
    if not bool(np.all(covered)):
        return None
    return out


def snapshot_runner_state(runner) -> Optional[dict]:
    """Host-side snapshot of the runner's TrainState assembled from LIVE
    LOCAL replicas (zero cross-process collectives, zero disk): the
    in-memory source for the post-reconfigure re-shard. Device leaves
    come from this process's addressable shards; host-PS-resident leaves
    come from the store (authoritative, process-local in the sync mirror
    mode that the in-run path supports) — the snapshot carries FULL
    original-layout trees, so the rebuilt DistributedStep's
    ``init_state`` re-seeds its fresh PSStore exactly like a cold start.
    None when any leaf is not locally reconstructible — state sharded
    across processes without a local replica has to come from the
    last-good checkpoint instead."""
    import jax
    state = runner.state
    if state is None:
        return None
    snapshot = {"step": None, "params": None, "opt_state": None,
                "sync_state": None}
    for kind in ("params", "opt_state", "sync_state"):
        tree = getattr(state, kind, None)
        ok = True

        def take(leaf):
            nonlocal ok
            full = _local_full_value(leaf)
            if full is None:
                ok = False
            return full
        host = jax.tree_util.tree_map(take, tree)
        if not ok:
            logging.warning(
                "elastic: %s is not fully locally replicated — the in-run "
                "re-shard will fall back to the last-good checkpoint", kind)
            return None
        snapshot[kind] = host
    dstep = runner.distributed_step
    store = getattr(dstep, "ps_store", None)
    if store is not None:
        # host-PS leaves are PSHole pytree nodes in the trees above (zero
        # leaves — the tree_map never saw them): fill them from the store
        # the same way gather_params/gather_opt_state do, so the snapshot
        # is the FULL checkpoint-layout state
        from autodist_tpu.parallel import ps as ps_lib
        try:
            store.drain()
            snapshot["params"] = ps_lib.fill_holes(snapshot["params"],
                                                   store.full_values())
            snapshot["opt_state"] = ps_lib.fill_holes_with_path(
                snapshot["opt_state"], store.full_opt_leaf)
        except Exception as e:  # noqa: BLE001 — a store whose owner died
            # with it (or an unreachable service) cannot seed the rebuild
            logging.warning(
                "elastic: host-PS state not locally reconstructible (%s) "
                "— the in-run re-shard will fall back to the last-good "
                "checkpoint", e)
            return None
    snapshot["step"] = int(np.asarray(_local_full_value(state.step)).ravel()[0])
    # the snapshot-time mesh: per-device sync_state leaves (ZeRO opt
    # shards, compressor residuals) are leading-device-axis arrays shaped
    # by THIS topology — the post-reconfigure adopt needs it to re-lay
    # the shards out onto the survivor mesh
    snapshot["mesh"] = {"axes": list(dstep.mesh.axis_names),
                        "shape": [int(dstep.mesh.shape[a])
                                  for a in dstep.mesh.axis_names],
                        "data_axis": dstep.mesh_axis}
    return snapshot


def _align_sync_state(sync_host, saved_mesh, dstep):
    """Align a snapshot's host sync_state to the REBUILT program's
    template: same-shape leaves carry over verbatim, ZeRO-sharded
    optimizer shards re-lay-out onto the new replica count (the same
    math the sharded checkpoint's cross-topology restore uses —
    shrinking a ZeroSharded job must not lose its adam moments), and
    any other shape-mismatched per-device leaf (compressor residuals,
    sentinel LR scale) resets to fresh init — topology-bound
    transients, documented safe."""
    import jax
    from autodist_tpu.kernel.common import variable_utils
    template = dstep._sync_state_init()
    names, leaves, treedef = variable_utils.flatten_named(template)
    have_names, have_leaves, _ = variable_utils.flatten_named(sync_host)
    have = dict(zip(have_names, have_leaves))
    zero_syncs = getattr(dstep, "zero_syncs", {}) or {}
    saved_mesh = saved_mesh or {}
    reset = []
    out = []
    for name, tmpl in zip(names, leaves):
        tmpl_np = np.asarray(tmpl)
        got = have.get(name)
        if got is not None and np.shape(got) == tmpl_np.shape:
            out.append(got)
            continue
        var = next((v for v in sorted(zero_syncs, key=len, reverse=True)
                    if name == "zero/%s" % v
                    or name.startswith("zero/%s/" % v)), None)
        if got is not None and var is not None and saved_mesh:
            from autodist_tpu.kernel.synchronization.zero_synchronizer \
                import relayout_zero_sync_leaf
            full = relayout_zero_sync_leaf(
                got, saved_mesh.get("axes", []),
                saved_mesh.get("shape", []),
                saved_mesh.get("data_axis", dstep.mesh_axis),
                zero_syncs[var], tmpl_np.shape, tmpl_np.dtype)
            if full is not None:
                out.append(full)
                continue
        out.append(tmpl)
        if got is not None:
            reset.append(name)
    if reset:
        logging.warning(
            "elastic: %d per-device sync leaves reset to fresh init "
            "across the topology change (topology-bound transients): %s",
            len(reset), reset[:4])
    return variable_utils.unflatten_named(treedef, out)


def adopt_snapshot(runner, snapshot: dict):
    """Re-lay the in-memory snapshot out onto the runner's (rebuilt) mesh
    — the same placement path the checkpoint restore uses
    (``Saver._restore_at``), minus the disk. Per-device sync_state
    leaves align through :func:`_align_sync_state` (ZeRO optimizer
    shards re-shard; residuals reset) — the snapshot was taken on the
    PRE-reconfigure topology."""
    import jax
    from autodist_tpu.train_state import TrainState
    dstep = runner.distributed_step
    sync_host = snapshot.get("sync_state")
    if sync_host is not None:
        sync_host = _align_sync_state(sync_host, snapshot.get("mesh"),
                                      dstep)
    state = dstep.init_state(snapshot["params"], snapshot["opt_state"],
                             sync_host)
    step = snapshot.get("step") or 0
    state = TrainState(
        step=dstep._put(np.asarray(step, np.int32),
                        jax.sharding.PartitionSpec()),
        params=state.params, opt_state=state.opt_state,
        sync_state=state.sync_state)
    runner.state = state
    notify = getattr(runner, "notify_state_restored", None)
    if callable(notify):
        notify()
    return state
