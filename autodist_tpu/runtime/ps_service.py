"""Async parameter-server serving over the native coordination service.

The reference implements asynchronous PS with C++ graph kernels: each
worker's update op pushes its gradient into a per-worker
``ConditionalAccumulator`` on the PS and applies without waiting for peers
(reference ``autodist/kernel/synchronization/ps_synchronizer.py:556-633``).
On TPU, async training cannot ride XLA collectives — they are lockstep by
construction — so the async wire is the native coordination service
(``native/coordination/coordination_service.cc``): the variable's owner
publishes versioned parameter blobs (``BPUT``), workers fetch the latest
(``BGET``) and push gradient blobs into a FIFO (``QPUSH``), and the owner's
apply thread drains the queue (``QPOP``), applying each worker's gradient
individually through the host store's optimizer — one gradient at a time,
no averaging barrier, exactly the reference's async semantics.

Under async PS every process runs its OWN local device mesh (the
reference's between-graph replication): gradients aggregate across local
replicas with local collectives, and the only cross-process coupling is
this service. Fetches always take the latest published version (pure
async, the reference's ``sync=False`` semantics); the only pacing is the
``ADT_PS_MAX_LAG`` backpressure bound on each owner queue. Bounded
staleness (``staleness=s``) belongs to SYNC training (the Runner's
coordination-service step window) and is rejected for async strategies.

``LocalPSService`` is the in-process degenerate case (single-process async:
the apply thread still decouples gradient application from stepping).
"""
import collections
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from autodist_tpu.runtime import elastic
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging

_MAGIC = b"ADPS"


def pack_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    """Self-describing binary packing of a {name: ndarray} dict.

    Layout: magic, count, then per entry: name_len/name/dtype_len/dtype/
    ndim/shape.../raw bytes. Names are sorted for determinism."""
    out = [_MAGIC, struct.pack("<I", len(arrays))]
    for name in sorted(arrays):
        arr = np.ascontiguousarray(arrays[name])
        nb = name.encode()
        dt = arr.dtype.str.encode()
        out.append(struct.pack("<H", len(nb)))
        out.append(nb)
        out.append(struct.pack("<H", len(dt)))
        out.append(dt)
        out.append(struct.pack("<B", arr.ndim))
        out.append(struct.pack("<%dq" % arr.ndim, *arr.shape))
        out.append(arr.tobytes())
    return b"".join(out)


def unpack_arrays(blob: bytes) -> Dict[str, np.ndarray]:
    if blob[:4] != _MAGIC:
        raise ValueError("not an ADPS blob")
    off = 4
    (count,) = struct.unpack_from("<I", blob, off)
    off += 4
    out = {}
    for _ in range(count):
        (nlen,) = struct.unpack_from("<H", blob, off)
        off += 2
        name = blob[off:off + nlen].decode()
        off += nlen
        (dlen,) = struct.unpack_from("<H", blob, off)
        off += 2
        dtype = np.dtype(blob[off:off + dlen].decode())
        off += dlen
        (ndim,) = struct.unpack_from("<B", blob, off)
        off += 1
        shape = struct.unpack_from("<%dq" % ndim, blob, off)
        off += 8 * ndim
        size = int(np.prod(shape or (1,))) * dtype.itemsize
        out[name] = np.frombuffer(blob, dtype, count=int(np.prod(shape or (1,))),
                                  offset=off).reshape(shape).copy()
        off += size
    return out


class PSServiceBase:
    """The wire the async PS path talks over (publish/fetch values, push/pop
    gradient blobs)."""

    def publish(self, version: int, blob: bytes) -> None:
        raise NotImplementedError

    def fetch(self) -> Optional[Tuple[int, bytes]]:
        raise NotImplementedError

    # optimizer-state side channel: published alongside values but only
    # FETCHED at checkpoint time — per-step pulls read the hot values
    # channel alone, so the wire per step stays ~value bytes instead of
    # value + moments (3x under Adam)
    def publish_opt(self, version: int, blob: bytes) -> None:
        raise NotImplementedError

    def fetch_opt(self) -> Optional[Tuple[int, bytes]]:
        raise NotImplementedError

    def push_grads(self, blob: bytes) -> None:
        raise NotImplementedError

    def pop_grads(self) -> Optional[bytes]:
        raise NotImplementedError

    def pending_grads(self) -> int:
        raise NotImplementedError

    def reconnect(self) -> None:
        """Drop this thread's transport so the next call re-establishes it
        (no-op for in-process services). Called by the owner apply loop
        after a transport error."""

    def close(self) -> None:
        pass


class LocalPSService(PSServiceBase):
    """In-process service (single-process async PS; also the unit-test
    harness for the serving protocol)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._published: Optional[Tuple[int, bytes]] = None
        self._published_opt: Optional[Tuple[int, bytes]] = None
        self._queue = collections.deque()

    def publish(self, version, blob):
        with self._lock:
            self._published = (version, blob)

    def fetch(self):
        with self._lock:
            return self._published

    def publish_opt(self, version, blob):
        with self._lock:
            self._published_opt = (version, blob)

    def fetch_opt(self):
        with self._lock:
            return self._published_opt

    def push_grads(self, blob):
        with self._lock:
            self._queue.append(blob)

    def pop_grads(self):
        with self._lock:
            return self._queue.popleft() if self._queue else None

    def pending_grads(self):
        with self._lock:
            return len(self._queue)


class CoordPSService(PSServiceBase):
    """Serving over the native coordination service. ``prefix`` isolates
    concurrent jobs on one service. Each talking thread needs its own
    socket; clients are created per-thread via the factory."""

    def __init__(self, client_factory: Callable, prefix: str = "ps"):
        self._factory = client_factory
        self._local = threading.local()
        self._prefix = prefix
        self._clients_lock = threading.Lock()
        self._clients = []  # every per-thread client, for close()
        self._closed = False

    def _client(self):
        if self._closed:
            # a thread may still hold a (now closed) client in its TLS;
            # fail with a clear error instead of a bad-fd OSError
            raise RuntimeError("CoordPSService is closed")
        if not hasattr(self._local, "client"):
            self._local.client = self._factory()
            with self._clients_lock:
                self._clients.append(self._local.client)
        return self._local.client

    def close(self):
        self._closed = True
        with self._clients_lock:
            clients, self._clients = self._clients, []
        for c in clients:
            try:
                c.close()
            except OSError:
                pass

    def publish(self, version, blob):
        # epoch-fenced (also enforced inside a resilient client's bput;
        # raw-client factories get the check here): a zombie owner must
        # not overwrite the values its replacement now serves
        elastic.maybe_fence("ps.publish")
        self._client().bput(self._prefix + "/vals", version, blob)

    def fetch(self):
        return self._client().bget(self._prefix + "/vals")

    def publish_opt(self, version, blob):
        elastic.maybe_fence("ps.publish_opt")
        self._client().bput(self._prefix + "/opt", version, blob)

    def fetch_opt(self):
        return self._client().bget(self._prefix + "/opt")

    def push_grads(self, blob):
        elastic.maybe_fence("ps.push")
        self._client().qpush(self._prefix + "/grads", blob)

    def pop_grads(self):
        return self._client().qpop(self._prefix + "/grads")

    def pending_grads(self):
        return self._client().qlen(self._prefix + "/grads")

    def reconnect(self):
        """Refresh the CALLING thread's transport after a service blip.
        A resilient client is asked to drop only its SOCKET (its circuit
        breaker and retry accounting survive — recreating the wrapper
        would re-pay the full retry budget on every probe); a raw client
        is discarded so the next call builds a fresh connection."""
        client = getattr(self._local, "client", None)
        if client is None:
            return
        if hasattr(client, "reconnect"):
            client.reconnect()
            return
        del self._local.client
        with self._clients_lock:
            if client in self._clients:
                self._clients.remove(client)
        try:
            client.close()
        except OSError:
            pass


class AsyncPSWorker:
    """The owner-side apply loop: drain gradient blobs, apply each through
    ``apply_fn``, republish ``values_fn()`` (the reference's per-worker
    accumulator apply, one gradient at a time — no barrier). ``opt_fn``
    (optional) provides the optimizer-state blob for the side channel —
    published with every apply so checkpoint reads stay fresh, but never
    downloaded by the per-step value pulls."""

    def __init__(self, service: PSServiceBase, apply_fn: Callable,
                 values_fn: Callable, poll_s: float = 0.002,
                 opt_fn: Optional[Callable] = None,
                 reconnect_budget_s: Optional[float] = None):
        self._apply_fn = apply_fn
        self._values_fn = values_fn
        self._opt_fn = opt_fn
        self._service = service
        self._poll_s = poll_s
        self._stop = threading.Event()
        self._pause = threading.Event()
        self._applied = 0
        self._busy = False  # a blob is popped but not yet applied
        # transport resilience: a service blip must not kill this thread —
        # it reconnects with backoff for up to reconnect_budget_s, then
        # declares itself UNHEALTHY (Runner fails the job loudly; silent
        # stall is the one forbidden outcome)
        if reconnect_budget_s is None:
            from autodist_tpu import const
            reconnect_budget_s = const.ENV.ADT_PS_OWNER_RETRY_S.val
        self._reconnect_budget_s = reconnect_budget_s
        self._last_error: Optional[BaseException] = None
        self._failed = False
        self._thread = threading.Thread(target=self._loop,
                                        name="adt-ps-apply", daemon=True)

    def start(self):
        # initial publish so workers can fetch before the first apply
        self._publish(0)
        self._thread.start()
        return self

    def _publish(self, version: int):
        with tel.span("ps_service.publish", "ps_service", version=version):
            self._service.publish(version, pack_arrays(self._values_fn()))
            if self._opt_fn is not None:
                self._service.publish_opt(version,
                                          pack_arrays(self._opt_fn()))
        tel.counter_add("ps_service.published")

    def _loop(self):
        while not self._stop.is_set():
            # busy is raised BEFORE the pause check AND before the pop:
            # pause() waits on !busy, so it can never return "quiesced"
            # while this thread is past the check and about to pop; and a
            # drain() racing the pop must never observe (queue empty, not
            # busy) while a blob is in hand
            self._busy = True
            if self._pause.is_set():
                self._busy = False
                time.sleep(self._poll_s)
                continue
            try:
                blob = self._service.pop_grads()
            except OSError as e:
                # transport error OUTSIDE the apply guard used to kill
                # this daemon thread silently and stall training forever;
                # now it degrades to reconnect-with-backoff
                self._busy = False
                if not self._recover(e, "pop_grads"):
                    return
                continue
            if blob is None:
                self._busy = False
                time.sleep(self._poll_s)
                continue
            try:
                with tel.span("ps_service.apply", "ps_service"):
                    self._apply_fn(unpack_arrays(blob))
                self._applied += 1
                tel.counter_add("ps_service.applied")
                self._publish(self._applied)
            except OSError as e:
                # the gradient IS applied locally; only the republish hit
                # the wire — reconnect and republish from the last applied
                # version (workers meanwhile serve their last fetch).
                # busy drops BEFORE the (potentially long) recovery:
                # nothing is in flight, and pause()/drain() must not
                # spuriously time out while a blip is being ridden out
                self._busy = False
                if not self._recover(e, "publish"):
                    return
            except elastic.FencedOut as e:
                # this owner was declared dead and superseded: its apply
                # loop must STOP — every further publish would fight the
                # replacement's state (healthy turns False; the Runner
                # fails the job loudly on its next step)
                self._failed = True
                self._last_error = e
                logging.error("async PS owner loop fenced out: %s", e)
                return
            except Exception as e:  # noqa: BLE001 — a poisoned blob must not kill the loop
                logging.error("async PS apply failed: %s", e)
            finally:
                self._busy = False

    def _recover(self, err: OSError, where: str) -> bool:
        """Reconnect after a transport error, republishing the CURRENT
        state (version = last applied) so workers resume from where the
        owner actually is — a restarted service starts blob-less, and
        without the republish every pull would wait on a publish that
        never comes. Returns False (loop exits, ``healthy`` turns False)
        once the retry budget is exhausted."""
        self._last_error = err
        logging.warning("async PS owner loop: transport error in %s (%s); "
                        "reconnecting for up to %.0fs", where, err,
                        self._reconnect_budget_s)
        deadline = time.monotonic() + self._reconnect_budget_s
        delay = 0.05
        while not self._stop.is_set():
            if time.monotonic() > deadline:
                self._failed = True
                logging.error(
                    "async PS owner loop DEAD: could not reach the "
                    "parameter service for %.0fs (last error: %s) — "
                    "training cannot make progress",
                    self._reconnect_budget_s, self._last_error)
                return False
            time.sleep(delay)
            delay = min(1.0, delay * 2)
            try:
                self._service.reconnect()
                self._publish(self._applied)
                logging.info("async PS owner loop: reconnected after %s "
                             "blip; republished version %d", where,
                             self._applied)
                self._last_error = None
                return True
            except OSError as e:
                self._last_error = e
        return False  # stopping: not a failure

    @property
    def applied(self) -> int:
        return self._applied

    @property
    def healthy(self) -> bool:
        """False once the apply loop is dead or past its reconnect budget
        — the owner can no longer apply gradients and the job must fail
        loudly instead of stalling."""
        if self._failed:
            return False
        if (self._thread.ident is not None and not self._thread.is_alive()
                and not self._stop.is_set()):
            return False  # thread died unexpectedly (bug / unhandled exc)
        return True

    @property
    def last_error(self) -> Optional[BaseException]:
        return self._last_error

    def publish_now(self):
        """Republish current values out of band (checkpoint restore) —
        fetch takes the latest publish (pure overwrite), so this replaces
        any pre-restore blob without disturbing the applied count."""
        self._publish(self._applied)

    def pause(self, timeout: float = 30.0):
        """Hold the apply loop and wait out any in-flight apply — state
        swaps (checkpoint restore) must not interleave with an apply.
        Queued blobs stay queued and apply after resume()."""
        self._pause.set()
        deadline = time.monotonic() + timeout
        while self._busy:
            if time.monotonic() > deadline:
                raise TimeoutError("async PS apply did not quiesce")
            time.sleep(self._poll_s)

    def resume(self):
        self._pause.clear()

    def drain(self, timeout: float = 30.0) -> int:
        """Block until the queue is empty and applied (tests/checkpoints)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._service.pending_grads() == 0 and not self._busy:
                return self._applied
            time.sleep(self._poll_s)
        raise TimeoutError("async PS queue did not drain")

    def stop(self) -> bool:
        """Stop the apply loop; True when the thread actually exited."""
        self._stop.set()
        self._thread.join(timeout=5)
        return not self._thread.is_alive()
