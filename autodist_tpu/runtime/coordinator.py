"""Coordinator — chief launches worker clients and supervises them.

Analog of reference ``autodist/coordinator.py:46-110``: on the chief, launch
*the same user script* (``python sys.argv``) on every worker host with env
``ADT_WORKER=<host>``/``ADT_STRATEGY_ID=<id>`` (the reference's
``AUTODIST_WORKER``/``AUTODIST_STRATEGY_ID``), first copying the serialized
strategy over; a watcher thread per remote process fail-fasts the whole job
(``os._exit(1)``) when any worker dies — the reference's exact supervision
semantics (``coordinator.py:98-110``).
"""
import atexit
import os
import shlex
import signal
import sys
import threading
import time

from typing import List

from autodist_tpu import const
from autodist_tpu.runtime.cluster import Cluster
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging

def _ere_escape(text: str) -> str:
    """Escape POSIX extended-regex metacharacters only (re.escape also
    backslashes ordinary characters like spaces, which POSIX ERE leaves
    undefined)."""
    return "".join("\\" + c if c in r".[]^$*+?(){}|\\" else c
                   for c in text)


def _reap_pattern(command: str) -> str:
    """pkill -f pattern matching ``command`` as a cmdline substring but
    NOT matching the pkill wrapper's own command line (first character
    wrapped in a regex bracket class, so the pattern text differs from
    the text it matches)."""
    esc = _ere_escape(command)
    return "[%s]%s" % (command[0], esc[len(_ere_escape(command[0])):])


def _reap_command(command: str, strategy_id: str) -> str:
    """Remote kill of a stale worker incarnation, scoped to THIS job.

    ``pkill -f <full command line>`` alone would match any process with
    the same argv — two concurrent jobs launched from the same script on
    a shared worker host would reap each other's live workers. The job's
    identity lives in the worker's environment (``ADT_STRATEGY_ID``,
    set at launch and inherited by children), not its argv (bash
    exec-optimizes the env-prefixed remote command, so assignments never
    appear in /proc cmdline). So: pgrep candidates by command line, then
    keep only pids whose ``/proc/<pid>/environ`` carries this job's
    strategy id. Wrapped in ``sh -c`` so the cluster's env prefix (a
    simple-command prefix) stays legal in front of the ``for`` loop."""
    script = (
        "for p in $(pgrep -f %s); do "
        "tr '\\0' '\\n' < /proc/$p/environ 2>/dev/null | grep -qxF %s "
        "&& kill -9 $p; done; true"
        % (shlex.quote(_reap_pattern(command)),
           shlex.quote("%s=%s" % (const.ENV.ADT_STRATEGY_ID.name_str,
                                  strategy_id))))
    return "sh -c %s" % shlex.quote(script)


class Coordinator:
    def __init__(self, strategy, cluster: Cluster,
                 heartbeat_timeout: float = None,
                 max_restarts: int = None):
        # a Strategy object, or just its id — the chief-launched flow
        # preallocates the id and launches workers BEFORE the strategy is
        # built (the chief's jax.distributed join blocks until every
        # worker connects, and building requires tracing, which would
        # initialize XLA before the join)
        self._strategy_id = getattr(strategy, "id", strategy)
        self._cluster = cluster
        self._threads: List[threading.Thread] = []
        self._heartbeat_timeout = (
            const.ENV.ADT_HEARTBEAT_TIMEOUT_S.val
            if heartbeat_timeout is None else heartbeat_timeout)
        # the cluster owns the service port (it starts the server)
        self._coordsvc_port = cluster.coordsvc_port
        self._stop_watchdog = threading.Event()
        # elastic recovery (beyond the reference's fail-fast-only
        # supervision): per-worker restart budget, sound only for async-PS
        # jobs (no collective lockstep to re-join; a relaunched worker
        # pulls current values from the parameter service on its first
        # step). _restart_unsound_reason() re-checks the strategy and the
        # elastic bring-up before the budget is ever used. The knobs are
        # validated LOUDLY (typed ElasticConfigError naming the knob) —
        # a typo'd budget must never silently disable elasticity.
        from autodist_tpu.runtime import elastic
        env_budget, _sync, self._inrun = elastic.validate_elastic_knobs()
        self._max_restarts = (env_budget if max_restarts is None
                              else max_restarts)
        self._restarts: dict = {}          # address -> restarts used
        self._restart_at: dict = {}        # address -> last relaunch time
        self._launch_cmds: dict = {}       # address -> (command, env)
        self._live_procs: dict = {}        # address -> current launcher proc
        # preemption plane: address -> announced departure deadline (wall
        # clock). A worker here is LEAVING ON PURPOSE — its heartbeat
        # silence is expected and its exit is shutdown (never failure).
        # Filled by BOTH the shrink planner and the watchdog's
        # _is_departing consultation, so it must NOT double as the
        # "shrink already published" memory — that lives in
        # _departures_handled (a consultation caching first would
        # otherwise suppress the planned shrink forever).
        self._planned_departures: dict = {}
        self._departures_handled: set = set()
        # addresses whose survivor epoch WAS published: only these skip
        # the process watcher's failure path on a nonzero exit — a
        # planned departure the chief could NOT shrink for (fail-fast
        # topology, chief leaving) must still take the whole-job restart
        # its log promises when the leaver dies
        self._departures_shrunk: set = set()
        # preempt/seq cursor: the planner re-scans the per-worker notice
        # marks only when a publish bumped it (one GET per tick steady)
        self._preempt_seq_seen: str = ""
        # sync-elastic (checkpoint-restore orchestration): worker death
        # restarts the WHOLE job from the latest checkpoint instead of
        # relaunching one worker. ADT_ELASTIC_SYNC at bring-up declares the
        # job sync-elastic from CONSTRUCTION — a worker dying in the join
        # window (before the chief has even built the strategy) must route
        # to the whole-job path, not the per-worker soundness gate; the
        # build path re-confirms via enable_sync_elastic()
        self._sync_elastic = (const.ENV.ADT_ELASTIC.val > 0
                              and const.ENV.ADT_ELASTIC_SYNC.val)
        atexit.register(self.join)

    def enable_sync_elastic(self):
        self._sync_elastic = True

    def _in_compile_grace(self, client, worker: str) -> bool:
        """True while ``worker`` holds a fresh one-shot ``compiling``
        mark (written by ``Runner._compile_grace_begin`` just before its
        first dispatch, cleared when the dispatch returns): heartbeat
        silence during first-dispatch XLA compilation is honest, not a
        hang. The mark is wall-clock (cross-process; minutes of grace
        make clock skew noise) and expires after ``ADT_COMPILE_GRACE_S``
        or twice the heartbeat window, whichever is larger — a worker
        that dies MID-compile still gets declared dead, just later."""
        try:
            mark = client.get("compiling/%s" % worker)
        except OSError:
            return False
        if not mark:
            return False
        try:
            ts = float(mark)
        except ValueError:
            return False
        grace = max(2 * self._heartbeat_timeout,
                    const.ENV.ADT_COMPILE_GRACE_S.val)
        if time.time() - ts < grace:
            logging.info("watchdog: worker %s missed heartbeats but is "
                         "inside its compile grace window — not aging it",
                         worker)
            return True
        return False

    def _is_straggling(self, client, worker: str) -> bool:
        """True while ``worker`` holds a fresh slow-but-alive mark
        (written by ``Runner._observe_straggler`` when its dispatch
        wall time EWMA-flags; cleared on recovery). Heartbeats are
        step-driven, so a straggler's beats slow WITH its steps — this
        mark is what separates "degraded but progressing" (leave it:
        the straggler attribution in the goodput report says why it is
        slow) from "dead" (recycle it). Freshness-bounded like the
        compile mark: a straggler that then truly dies stops refreshing
        the mark and is declared dead one grace window later."""
        try:
            mark = client.get("straggler/%s" % worker)
        except OSError:
            return False
        if not mark:
            return False
        try:
            ts = float(mark)
        except ValueError:
            return False
        if ts <= 0:
            return False  # "0" = explicitly cleared
        if time.time() - ts < 2 * self._heartbeat_timeout:
            logging.warning(
                "watchdog: worker %s missed heartbeats but marked itself "
                "a straggler (slow-but-alive) — not recycling it; see "
                "`python -m autodist_tpu.telemetry goodput` for the "
                "attribution", worker)
            return True
        return False

    def _is_departing(self, client, worker: str) -> bool:
        """True while ``worker`` holds a live preemption notice (or its
        announced deadline has not aged out): its heartbeat silence and
        process exit are an ANNOUNCED departure mid-handoff, and routing
        it to the unplanned-death path (shrink escalation + mark GC)
        would race — and corrupt — the graceful handoff it is running.
        Consulted BEFORE any dead declaration (the planned-departure
        satellite of the preemption plane)."""
        deadline = self._planned_departures.get(worker)
        if deadline is None:
            from autodist_tpu.runtime import preemption
            try:
                notice = preemption.read_notice(client, worker)
            except OSError:
                return False
            if notice is None:
                return False
            deadline = notice.deadline
            self._planned_departures[worker] = deadline
        # grace past the deadline: the platform's SIGKILL and the exit
        # propagation take a moment; afterwards the departure is complete
        # and normal (dead) accounting may resume for the NEXT incarnation
        if time.time() < deadline + 2 * self._heartbeat_timeout:
            logging.info(
                "watchdog: worker %s missed heartbeats but announced its "
                "departure — expected, not escalating", worker)
            return True
        self._planned_departures.pop(worker, None)
        return False

    def start_watchdog(self):
        """Heartbeat-based failure detection via the coordination service
        (augments the process-exit watcher): a worker that stops heartbeating
        for ``heartbeat_timeout`` seconds fails the job fast."""
        from autodist_tpu.runtime.coordination import CoordinationClient

        def connect_with_backoff():
            """(Re)establish the watchdog's client, retrying with capped
            backoff until connected or the job stops. The one-shot client
            this replaces meant a single service blip permanently
            disabled heartbeat supervision — silently."""
            delay = 0.5
            while not self._stop_watchdog.is_set():
                try:
                    # finite RPC deadline: a hung (not just dead) service
                    # must surface as a timeout, not park the watchdog
                    return CoordinationClient(
                        "127.0.0.1", self._coordsvc_port,
                        timeout=max(5.0, self._heartbeat_timeout / 2))
                except OSError as e:
                    logging.warning(
                        "watchdog: coordination service unreachable on "
                        "port %d (%s) — heartbeat supervision DEGRADED; "
                        "retrying in %.1fs", self._coordsvc_port, e, delay)
                    if self._stop_watchdog.wait(delay):
                        return None
                    delay = min(delay * 2, self._heartbeat_timeout / 2)
            return None

        def watch():
            client = connect_with_backoff()
            if client is None:
                return
            while not self._stop_watchdog.wait(self._heartbeat_timeout / 4):
                try:
                    dead = client.dead_workers(self._heartbeat_timeout)
                except OSError as e:
                    logging.warning(
                        "watchdog: lost the coordination service (%s) — "
                        "supervision degraded until reconnect", e)
                    try:
                        client.close()
                    except OSError:
                        pass
                    client = connect_with_backoff()
                    if client is None:
                        return
                    logging.info("watchdog: coordination service client "
                                 "re-established; supervision resumed")
                    continue
                # grow-on-join: a relaunched/hot-spare worker announced
                # itself — publish the grown roster at the next epoch so
                # the survivors (and the joiner) expand the job back
                try:
                    self._maybe_admit_joiners(client)
                except OSError:
                    pass  # service blip: the next tick retries
                # preemption: an ANNOUNCED departure is handled while the
                # leaver is still alive — publish the survivor roster now
                # (no detection latency, no false-death escalation)
                try:
                    self._maybe_plan_departures(client)
                except OSError:
                    pass  # service blip: the next tick retries
                # elastic-aware: a worker with restart budget left may be
                # mid-relaunch (import + trace + compile easily exceeds the
                # heartbeat window) — skip anything inside a fresh
                # incarnation's bring-up grace (a killed incarnation is
                # deregistered at relaunch, so this covers only records
                # the new incarnation itself wrote). Outside the grace: a
                # worker WITH budget whose process is still alive is
                # deadlocked — kill AND deregister it so the process
                # watcher relaunches it without a stale record aging
                # against the replacement (silence is the only deadlock
                # signal an async job emits); a worker without budget is
                # fatal.
                now = time.monotonic()
                dead = [d for d in dead if d != "chief"
                        and now - self._restart_at.get(d, float("-inf"))
                        > 2 * self._heartbeat_timeout]
                # first-dispatch compilation grace: a worker that marked
                # itself "compiling" (Runner._compile_grace_begin) is in
                # a legitimately silent XLA compile — a long fused-k or
                # big-bucket lowering easily exceeds the heartbeat
                # window, and killing it would be a false death
                dead = [d for d in dead
                        if not self._in_compile_grace(client, d)]
                # slow-but-alive stragglers (fresh straggler/<worker>
                # mark) are degraded, not dead: recycling one mid-window
                # would turn a throttled host into a real outage
                dead = [d for d in dead
                        if not self._is_straggling(client, d)]
                # announced departures: the leaver's silence is the
                # handoff, not a death — the planned-shrink path above
                # (_maybe_plan_departures) already owns its recovery
                dead = [d for d in dead
                        if not self._is_departing(client, d)]
                fatal = [d for d in dead
                         if self._max_restarts <= self._restarts.get(d, 0)]
                for d in dead:
                    if d in fatal:
                        continue
                    proc = self._live_procs.get(d)
                    if proc is not None and proc.poll() is None:
                        # defense in depth: only kill when SOME recovery is
                        # sound — a per-worker relaunch (async), or the
                        # whole-job checkpoint-restore restart (sync-
                        # elastic: killing the wedged worker routes its
                        # death to _restart_whole_job via the process
                        # watcher). Note sync workers only write heartbeat
                        # records in staleness-pacing modes; a silent
                        # sync wedge otherwise surfaces as a collective
                        # timeout -> process death -> the same path.
                        if (not self._sync_elastic
                                and self._restart_unsound_reason(d)
                                is not None):
                            logging.warning(
                                "worker %s missed heartbeats but a restart "
                                "would be unsound — not killing it", d)
                            continue
                        logging.warning(
                            "worker %s missed heartbeats but its process is "
                            "alive (deadlock?) — killing it for an elastic "
                            "restart", d)
                        try:
                            os.killpg(proc.pid, signal.SIGKILL)
                            proc.wait(timeout=5)
                            killed = True
                        except Exception:  # noqa: BLE001
                            killed = False
                            logging.error(
                                "could not kill wedged worker %s; keeping "
                                "its liveness record so this stays visible",
                                d)
                        if killed:
                            # deregister ONLY once the process is confirmed
                            # gone: erasing the record of a still-wedged
                            # worker would hide the hang forever
                            try:
                                client.goodbye(d)
                            except OSError:
                                pass
                    else:
                        logging.warning(
                            "worker %s missed heartbeats; restart budget "
                            "remains — leaving it to the process watcher", d)
                if fatal:
                    logging.error("workers %s missed heartbeats — aborting",
                                  fatal)
                    os._exit(1)
        t = threading.Thread(target=watch, daemon=True)
        t.start()
        self._threads.append(t)

    def distribute_strategy(self):
        """Copy the serialized strategy to every worker host (chief-side;
        workers poll for the file by id). In the chief-launched flow this
        runs AFTER the workers are already up — they wait in their
        strategy poll until the file lands."""
        strategy_path = os.path.join(const.DEFAULT_SERIALIZATION_DIR,
                                     self._strategy_id)
        for address in self._cluster.process_addresses:
            if not self._cluster.is_chief(address):
                self._cluster.remote_copy(
                    strategy_path, const.DEFAULT_SERIALIZATION_DIR, address)

    def launch_clients(self, copy_strategy: bool = True):
        """Relaunch this script on every non-chief host."""
        script = os.path.abspath(sys.argv[0])
        argv_rest = " ".join(sys.argv[1:])
        if copy_strategy:
            self.distribute_strategy()
        for address in self._cluster.process_addresses:
            if self._cluster.is_chief(address):
                continue
            env = self._cluster.worker_env(address)
            env[const.ENV.ADT_STRATEGY_ID.name_str] = self._strategy_id
            # propagate the debugging/testing knobs only when explicitly set
            # locally — an empty string would override the worker's default
            # (reference coordinator.py:70-79)
            for e in (const.ENV.ADT_MIN_LOG_LEVEL, const.ENV.ADT_IS_TESTING,
                      const.ENV.ADT_PATCH_OPTAX, const.ENV.ADT_ELASTIC,
                      const.ENV.ADT_ELASTIC_SYNC, const.ENV.ADT_AUTO_RESUME,
                      const.ENV.ADT_CKPT_DIR, const.ENV.ADT_ELASTIC_EXCLUDE,
                      const.ENV.ADT_ELASTIC_INRUN,
                      const.ENV.ADT_ELASTIC_POLL_S,
                      const.ENV.ADT_ELASTIC_ACK_TIMEOUT_S,
                      const.ENV.ADT_HEARTBEAT_TIMEOUT_S):
                raw = os.environ.get(e.name_str)
                if raw is not None:
                    env[e.name_str] = raw
            # from the cluster field, not the chief's env: an explicit
            # coordsvc_port constructor arg must reach the workers too
            env[const.ENV.ADT_COORDSVC_PORT.name_str] = str(self._coordsvc_port)
            command = "python -u %s %s" % (script, argv_rest)
            self._launch_cmds[address] = (command, env)
            proc = self._cluster.remote_exec(command, address, env=env)
            if proc is not None:
                self._live_procs[address] = proc
                self._proc_wait_async(proc, address)
            logging.info("launched worker client on %s (process %d)",
                         address, self._cluster.process_id(address))

    def _proc_wait_async(self, proc, address: str):
        """Fail-fast watcher (reference ``coordinator.py:98-110``). A
        worker death after the job finished cleanly (``stop_watchdog``
        set — e.g. the chief's exit-time terminate SIGTERMing a trailing
        worker) is shutdown, not failure, and must not abort a
        successful run with exit code 1. With an elastic budget
        (``ADT_ELASTIC``), a restartable worker is relaunched instead."""
        def watch():
            code = proc.wait()
            if code != 0 and not self._stop_watchdog.is_set():
                if address in self._departures_shrunk:
                    # an announced leaver whose survivor shrink WAS
                    # published: its exit is shutdown, not failure —
                    # even a nonzero code (the platform's deadline
                    # SIGKILL) must not abort the survivors or burn a
                    # restart. (A planned departure the chief could NOT
                    # shrink for falls through to _try_restart: the
                    # whole-job restart is its recovery.) Scrub its
                    # liveness records so the stale beat never ages
                    # against a future incarnation.
                    logging.warning(
                        "preemption: announced leaver %s exited with code "
                        "%s — planned departure complete", address, code)
                    try:
                        from autodist_tpu.runtime import elastic
                        c = self._coordsvc_client()
                        elastic.gc_worker_marks(c, address)
                        c.close()
                    except OSError:
                        pass
                    return
                try:
                    restarted = self._try_restart(address, code, proc)
                except Exception as e:  # noqa: BLE001 — a broken restart
                    # path must degrade to fail-fast, never to a silently
                    # dead watcher (the worker IS down at this point)
                    logging.error("elastic restart of %s failed: %s", address, e)
                    restarted = False
                if restarted:
                    return
                logging.error("worker %s exited with code %s — aborting job",
                              address, code)
                os._exit(1)
        t = threading.Thread(target=watch, daemon=True)
        t.start()
        self._threads.append(t)

    # ------------------------------------------------------ elastic recovery

    def _try_restart(self, address: str, code, old_proc=None) -> bool:
        """Relaunch a dead worker when (a) restart budget remains and
        (b) the job's strategy makes a restart SOUND. Returns True when a
        relaunch happened (the new process is supervised like the first).

        Sync-elastic jobs prefer the IN-RUN shrink (ADT_ELASTIC_INRUN):
        publish the survivor roster at epoch+1 and let the survivors
        re-form a smaller mesh at their next readback boundary — no
        re-exec, no disk round-trip. When the topology cannot shrink (or
        the survivors never ack — wedged in a collective the dead worker
        will never re-enter), fall back to the whole-job path: tear-down +
        relaunch-from-checkpoint."""
        if self._sync_elastic:
            try:
                if self._shrink_to_survivors(address, code):
                    return True
            except Exception as e:  # noqa: BLE001 — a broken shrink path
                # must degrade to the proven whole-job restart
                logging.error("in-run elastic shrink failed (%s); falling "
                              "back to whole-job restart", e)
            return self._restart_whole_job(address, code)
        used = self._restarts.get(address, 0)
        if self._max_restarts <= used or address not in self._launch_cmds:
            return False
        command, env = self._launch_cmds[address]
        # reap FIRST — right after proc.wait() returned, before the (file
        # IO) soundness gate — to keep the pgid-reuse window minimal and
        # ensure no orphan survivor outlives this decision either way
        self._reap_incarnation(address, command, old_proc)
        reason = self._restart_unsound_reason(address)
        if reason is not None:
            logging.error("worker %s died (code %s) but elastic restart is "
                          "unsound for this job: %s — failing fast",
                          address, code, reason)
            return False
        self._restarts[address] = used + 1
        self._restart_at[address] = time.monotonic()
        # scrub the dead incarnation's liveness records (a crashed or
        # SIGKILLed worker never said GOODBYE): its stale heartbeat must
        # not age against the replacement while it compiles, and its
        # compiling/straggler marks must not satisfy (or poison) the
        # watchdog's freshness checks against the NEXT incarnation
        try:
            from autodist_tpu.runtime import elastic
            from autodist_tpu.runtime.coordination import CoordinationClient
            c = CoordinationClient("127.0.0.1", self._coordsvc_port)
            elastic.gc_worker_marks(c, address)
            c.close()
        except OSError:
            pass  # no service (or unreachable): the bring-up grace covers it
        logging.warning("worker %s exited with code %s — relaunching worker "
                        "(restart %d/%d)", address, code,
                        self._restarts[address], self._max_restarts)
        proc = self._cluster.remote_exec(command, address, env=env)
        if proc is None:  # dry-run mode: nothing to supervise
            return True
        self._live_procs[address] = proc
        self._proc_wait_async(proc, address)
        return True

    # -------------------------------------------- in-run elastic (epoch-fenced)

    def _coordsvc_client(self):
        from autodist_tpu.runtime.coordination import CoordinationClient
        return CoordinationClient("127.0.0.1", self._coordsvc_port,
                                  timeout=max(5.0,
                                              self._heartbeat_timeout / 2))

    def _shrink_unsound_reason(self, address: str):
        """None when the survivors can re-form a smaller mesh in-run after
        ``address`` dies; otherwise why not (the caller then degrades to
        the whole-job checkpoint restart). Mirrors the analysis plane's
        ADT430/431 rules (``analysis/rules.py verify_elastic``) so the
        pre-compile lint and the runtime decision can never disagree."""
        from autodist_tpu.strategy.base import Strategy
        try:
            strategy = Strategy.deserialize(self._strategy_id)
        except (OSError, ValueError) as e:
            return "strategy %s unreadable (%s)" % (self._strategy_id, e)
        from autodist_tpu.analysis import rules as rules_lib
        diags = rules_lib.verify_elastic(strategy, dead_worker=address)
        errors = [d for d in diags if d.code == "ADT430"]
        if errors:
            return errors[0].message
        if any(d.code == "ADT431" for d in diags):
            # dead PS-owner groups: the in-memory path cannot reassemble
            # state that died with its sole owner — in-run shrink is still
            # sound IF a committed checkpoint exists for the fallback
            from autodist_tpu.checkpoint import latest_checkpoint
            found, _ = latest_checkpoint(const.ENV.ADT_CKPT_DIR.val)
            if found is None:
                return ("worker %s owns PS state (ADT431) and no committed "
                        "checkpoint exists for the fallback re-shard"
                        % address)
        return None

    def _shrink_to_survivors(self, address: str, code) -> bool:
        """In-run shrink: publish ``epoch+1`` with the survivor roster so
        every survivor re-forms the smaller process set at its next
        readback boundary (Runner._maybe_reconfigure). Spends one restart
        from the elastic budget per reconfiguration. Returns False when
        in-run elasticity is off / unsound — the caller falls back to the
        whole-job restart. The dead worker is relaunched afterwards (if
        budget remains) so it can re-join via the admission protocol and
        grow the job back."""
        from autodist_tpu.runtime import elastic
        if not self._inrun:
            return False
        used = self._restarts.get(address, 0)
        if self._max_restarts <= used:
            logging.error("in-run elastic: worker %s died (code %s) but "
                          "its restart budget (%d) is spent", address,
                          code, self._max_restarts)
            return False
        reason = self._shrink_unsound_reason(address)
        if reason is not None:
            logging.error("in-run elastic: cannot shrink past worker %s: "
                          "%s — falling back to whole-job restart",
                          address, reason)
            return False
        # the dead incarnation must be REALLY gone before its peers adopt
        # a roster without it — a half-dead straggler would be exactly the
        # zombie the epoch fence exists for, but reaping first shrinks
        # the window in which the fence is the only defense
        command_env = self._launch_cmds.get(address)
        if command_env is not None:
            self._reap_incarnation(address, command_env[0],
                                   self._live_procs.get(address))
        client = self._coordsvc_client()
        try:
            elastic.gc_worker_marks(client, address)
            info = elastic.read_epoch(client)
            if info is None:
                logging.error("in-run elastic: no epoch was ever published "
                              "(arm_inrun_elastic not called?) — falling "
                              "back to whole-job restart")
                return False
            epoch, roster = info
            if address not in roster:
                # already shrunk away (this is its relaunch-for-rejoin
                # dying before admission): the roster is correct as-is —
                # burn a restart on another relaunch attempt, no epoch
                self._restarts[address] = used + 1
                self._restart_at[address] = time.monotonic()
                if command_env is not None:
                    command, env = command_env
                    proc = self._cluster.remote_exec(command, address,
                                                     env=env)
                    if proc is not None:
                        self._live_procs[address] = proc
                        self._proc_wait_async(proc, address)
                    logging.warning(
                        "in-run elastic: pre-admission relaunch of %s died "
                        "(code %s) — relaunching again (restart %d/%d)",
                        address, code, used + 1, self._max_restarts)
                return True
            survivors = [a for a in roster if a != address]
            if not survivors:
                return False
            elastic.publish_epoch(client, epoch + 1, survivors)
        finally:
            try:
                client.close()
            except OSError:
                pass
        self._restarts[address] = used + 1
        self._restart_at[address] = time.monotonic()
        tel.counter_add("elastic.shrinks")
        logging.warning(
            "in-run elastic: worker %s died (code %s) — published epoch %d "
            "with %d survivor(s); the job shrinks at the next readback "
            "boundary (restart %d/%d)", address, code, epoch + 1,
            len(survivors), self._restarts[address], self._max_restarts)
        # escalation: survivors that never ack (wedged in a collective the
        # dead worker will never re-enter) get the whole-job restart
        t = threading.Thread(target=self._watch_acks,
                             args=(epoch + 1, survivors, address, code),
                             daemon=True)
        t.start()
        self._threads.append(t)
        # relaunch the dead worker so it can announce itself and grow the
        # job back (admission is the watchdog's _maybe_admit_joiners)
        if command_env is not None:
            command, env = command_env
            proc = self._cluster.remote_exec(command, address, env=env)
            if proc is not None:
                self._live_procs[address] = proc
                self._proc_wait_async(proc, address)
            logging.info("in-run elastic: relaunched %s for grow-on-join",
                         address)
        return True

    def _watch_acks(self, epoch: int, roster, address: str, code):
        """Wait for every survivor's ``elastic/ack/<epoch>/<worker>``;
        escalate to the whole-job checkpoint restart when the shrink never
        completes (ADT_ELASTIC_ACK_TIMEOUT_S)."""
        deadline = time.monotonic() + const.ENV.ADT_ELASTIC_ACK_TIMEOUT_S.val
        pending = set(roster)
        client = None
        while not self._stop_watchdog.is_set():
            if client is None:
                try:
                    client = self._coordsvc_client()
                except OSError:
                    if self._stop_watchdog.wait(1.0):
                        return
                    continue
            try:
                for w in sorted(pending):
                    if client.get("elastic/ack/%d/%s" % (epoch, w)):
                        pending.discard(w)
            except OSError:
                try:
                    client.close()
                except OSError:
                    pass
                client = None
            if not pending:
                logging.info("in-run elastic: every survivor acked "
                             "epoch %d", epoch)
                tel.counter_add("elastic.reconfigs_acked")
                if client is not None:
                    client.close()
                return
            if time.monotonic() > deadline:
                logging.error(
                    "in-run elastic: survivors %s never acked epoch %d "
                    "within %.0fs — escalating to the whole-job restart",
                    sorted(pending), epoch,
                    const.ENV.ADT_ELASTIC_ACK_TIMEOUT_S.val)
                if client is not None:
                    client.close()
                self._restart_whole_job(address, code)
                return
            if self._stop_watchdog.wait(0.25):
                return

    def _maybe_plan_departures(self, client):
        """Planned handoff, chief side: a rostered worker published a
        preemption notice — publish the survivor roster at epoch+1 NOW,
        while the leaver is still alive and lockstep. The survivors
        reconfigure at their next readback boundary with step-exact live
        replicas (no checkpoint fallback, no watchdog detection
        latency); the leaver — excluded from the new roster — runs its
        graceful departure instead of the zombie fence-out. No reap, no
        relaunch, no restart-budget spend: the host is being taken away,
        not recovered."""
        from autodist_tpu.runtime import elastic, preemption
        if not self._inrun:
            return
        # one-key steady state: scan the per-worker marks only when a
        # publish bumped preempt/seq (the same cursor the runner-side
        # guard polls). The cursor is consumed only after a FULL scan
        # that published nothing — a tick that planned one shrink leaves
        # it unconsumed so any second notice is planned next tick.
        seq = client.get(preemption.SEQ_KEY) or ""
        if seq == self._preempt_seq_seen:
            return
        info = elastic.read_epoch(client)
        if info is None:
            return
        epoch, roster = info
        for addr in roster:
            if addr in self._departures_handled:
                continue  # this departure's shrink decision is made
            notice = preemption.read_notice(client, addr)
            if notice is None or preemption.has_left(client, addr):
                continue
            self._departures_handled.add(addr)
            self._planned_departures[addr] = notice.deadline
            if addr == "chief" or self._cluster.is_chief(addr):
                logging.error(
                    "preemption: the CHIEF announced departure (%s) — a "
                    "chief handoff needs external re-election; relying on "
                    "the rescue checkpoint + ADT_AUTO_RESUME relaunch",
                    notice.reason)
                continue
            reason = self._shrink_unsound_reason(addr)
            if reason is not None:
                logging.error(
                    "preemption: %s announced departure but the topology "
                    "cannot shrink past it (%s) — it departs with its "
                    "rescue checkpoint and the job takes the whole-job "
                    "restart when it exits", addr, reason)
                continue
            survivors = [a for a in roster if a != addr]
            if not survivors:
                continue  # last worker standing: nothing to shrink to
            elastic.publish_epoch(client, epoch + 1, survivors)
            self._departures_shrunk.add(addr)
            tel.counter_add("preempt.planned_shrinks")
            tel.instant("preempt.planned_shrink", "preempt", worker=addr,
                        epoch=epoch + 1, world=len(survivors),
                        reason=notice.reason)
            logging.warning(
                "preemption: planned shrink for announced leaver %s (%s, "
                "%.1fs of grace) — published epoch %d with %d "
                "survivor(s); the leaver hands off ALIVE at its next "
                "boundary", addr, notice.reason,
                max(notice.remaining_s(), 0.0), epoch + 1, len(survivors))
            # same escalation ladder as the unplanned shrink: survivors
            # that never ack get the whole-job checkpoint restart
            t = threading.Thread(
                target=self._watch_acks,
                args=(epoch + 1, survivors, addr, "preempted"),
                daemon=True)
            t.start()
            self._threads.append(t)
            return  # one membership change per tick (cursor unconsumed)
        self._preempt_seq_seen = seq

    def _maybe_admit_joiners(self, client):
        """Grow-on-join: admit relaunched/hot-spare workers that announced
        themselves (``elastic/join/<worker>``) by publishing the grown
        roster at the next epoch. Candidates are the addresses this chief
        launched that the current roster excludes. Also GCs non-roster
        workers' stale liveness marks so a dead incarnation can never
        satisfy a freshness check across epochs."""
        from autodist_tpu.runtime import elastic
        if not self._inrun:
            return
        info = elastic.read_epoch(client)
        if info is None:
            return
        epoch, roster = info
        outsiders = [a for a in self._launch_cmds if a not in roster]
        joiners = []
        for a in outsiders:
            if elastic.pending_join(client, a):
                joiners.append(a)
            else:
                # roster hygiene: a worker outside the roster must hold no
                # live heartbeat/compiling/straggler records
                elastic.gc_worker_marks(client, a)
        if not joiners:
            return
        from autodist_tpu.runtime import preemption
        for a in joiners:
            elastic.clear_join(client, a)
            elastic.gc_worker_marks(client, a)
            # a previous incarnation's departure notice must not make
            # the watchdog treat the NEW incarnation as leaving — and a
            # future departure of the same address must plan afresh
            preemption.clear_notice(client, a)
            self._planned_departures.pop(a, None)
            self._departures_handled.discard(a)
            self._departures_shrunk.discard(a)
        grown = roster + sorted(joiners)
        elastic.publish_epoch(client, epoch + 1, grown)
        tel.counter_add("elastic.grows")
        logging.warning(
            "in-run elastic: admitted %s — published epoch %d with %d "
            "member(s); the job grows at the next readback boundary",
            ",".join(joiners), epoch + 1, len(grown))

    def _restart_whole_job(self, address: str, code) -> bool:
        """Sync-elastic recovery: a worker died mid-lockstep, so the
        surviving processes (including THIS chief, whose main thread is
        wedged in a collective the dead worker will never re-enter) cannot
        continue. Reap every worker incarnation, then re-exec the chief's
        own script with ``ADT_AUTO_RESUME=1`` — the fresh run relaunches
        the workers, and every process restores the latest checkpoint
        (``Runner.init``'s auto-resume) before training resumes. The
        restart budget is carried across the exec in
        ``ADT_ELASTIC_RESTARTS``. Returns False (fail-fast) when the
        budget is spent."""
        used = int(os.environ.get("ADT_ELASTIC_RESTARTS", "0"))
        if used >= self._max_restarts or not self._launch_cmds:
            logging.error(
                "sync-elastic: worker %s died (code %s) but the restart "
                "budget (%d) is spent — failing fast", address, code,
                self._max_restarts)
            return False
        # same probe the runner's auto-resume uses — the nothing-to-restore
        # fail-fast here and the actual resume there must agree
        from autodist_tpu.checkpoint import latest_checkpoint
        ckpt_dir = const.ENV.ADT_CKPT_DIR.val
        found, _saver = latest_checkpoint(ckpt_dir)
        cur_step = -1 if found is None else found
        if cur_step < 0:
            logging.error(
                "sync-elastic: worker %s died (code %s) before any "
                "checkpoint landed in %s — nothing to restore, failing "
                "fast (save at least once per restart window)", address,
                code, ckpt_dir)
            return False
        # permanently-lost detection: a worker whose death triggers two
        # whole-job restarts WITHOUT checkpoint progress in between (it
        # died, the job restarted, it died again before any new step was
        # committed) is excluded — the restarted job runs at REDUCED world
        # size, with the cross-topology sharded restore
        # (checkpoint/sharded.py) reassembling the survivors' state. The
        # checkpoint-step guard keeps transient preemptions hours apart
        # from decommissioning a healthy host: any committed progress
        # resets the "consecutive" condition.
        last_dead = os.environ.get("ADT_ELASTIC_LAST_DEAD", "")
        last_step = int(os.environ.get("ADT_ELASTIC_LAST_DEAD_STEP", "-1"))
        exclude = [a for a in
                   os.environ.get(const.ENV.ADT_ELASTIC_EXCLUDE.name_str,
                                  "").split(",") if a]
        if (address == last_dead and cur_step <= last_step
                and address not in exclude):
            exclude.append(address)
            os.environ[const.ENV.ADT_ELASTIC_EXCLUDE.name_str] = (
                ",".join(exclude))
            logging.error(
                "sync-elastic: worker %s died twice with no checkpoint "
                "progress (still at step %d) — treating it as PERMANENTLY "
                "lost; the job restarts at reduced world size without it "
                "(excluded: %s)", address, cur_step, exclude)
        os.environ["ADT_ELASTIC_LAST_DEAD"] = address
        os.environ["ADT_ELASTIC_LAST_DEAD_STEP"] = str(cur_step)
        logging.warning(
            "sync-elastic: worker %s died (code %s) mid-lockstep — "
            "restarting the WHOLE job from the latest checkpoint "
            "(restart %d/%d)", address, code, used + 1, self._max_restarts)
        # silence the other watchers first: the reap below kills their
        # processes, which must read as shutdown, not as fresh failures
        self._stop_watchdog.set()
        for addr, (command, _env) in sorted(self._launch_cmds.items()):
            try:
                self._reap_incarnation(addr, command,
                                       self._live_procs.get(addr))
            except Exception as e:  # noqa: BLE001 — best-effort teardown
                logging.warning("reap of %s failed: %s", addr, e)
        # stop the coordination-service child: exec skips atexit, and an
        # orphan would hold the port (EADDRINUSE for the resumed job's
        # fresh server) while carrying stale heartbeat/queue/barrier state
        self._cluster.stop_coordination_service()
        os.environ["ADT_ELASTIC_RESTARTS"] = str(used + 1)
        os.environ[const.ENV.ADT_AUTO_RESUME.name_str] = "1"
        # scrub what THIS incarnation's _setup exported: the fresh chief
        # must look like a first start (else maybe_init_distributed joins
        # from the inherited process count BEFORE the workers are launched
        # and wedges waiting for them)
        os.environ.pop(const.ENV.ADT_NUM_PROCESSES.name_str, None)
        os.environ.pop(const.ENV.ADT_STRATEGY_ID.name_str, None)
        logging.warning("sync-elastic: re-exec %s %s", sys.executable,
                        " ".join(sys.argv))
        sys.stdout.flush()
        sys.stderr.flush()
        # exec replaces the process image: the wedged main thread, the
        # jax.distributed client state, and the atexit chain all go with it
        os.execv(sys.executable, [sys.executable] + sys.argv)

    def _reap_incarnation(self, address: str, command: str, old_proc):
        """Make sure the PREVIOUS incarnation is really gone before its
        replacement starts: the watcher observes the LOCAL launcher process
        (for ssh transport, the ssh client), which can die — network blip,
        ssh killed — while the remote worker keeps training. Two live
        incarnations under one worker identity would both push gradients.

        Local transport: SIGKILL the old process group. setsid at launch
        makes pgid == the launcher pid, and the group id stays valid while
        ANY member survives — even after the leader was reaped by
        ``proc.wait()``. If the WHOLE group is gone the pid could in
        principle be recycled, but a recycled pid is a process-group id
        only if its new holder itself called setsid — this killpg runs
        immediately after ``proc.wait()`` returned, so the window is tiny.

        Remote transport: kill the exact launched command line on the
        remote host (the reference's stale-server cleanup approach,
        ``utils/server_starter.py:29-46``), scoped to this job's strategy
        id via /proc environ so concurrent jobs sharing a worker host and
        argv never reap each other (``_reap_command``)."""
        if old_proc is not None:
            try:
                os.killpg(old_proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                pass
        if not self._cluster._is_local(address):
            self._cluster.remote_exec(
                _reap_command(command, self._strategy_id), address, wait=True)

    def _restart_unsound_reason(self, address: str):
        """None when every variable syncs through async host-PS owned by a
        surviving host; otherwise why a restart would corrupt the job.
        Sync strategies are collective-lockstep (the peers are wedged in a
        collective the dead worker will never re-enter at the same program
        point), and any PS group owned by the dead worker took its
        authoritative state down with it — both must fail fast (resume
        from a checkpoint instead).

        Deliberately CONSERVATIVE: this reads the raw serialized strategy,
        so a config the running job itself skips (e.g. a sync node for a
        frozen/pruned var) can refuse a restart the job could survive —
        over-strictness degrades to the reference's fail-fast, never to a
        corrupted run."""
        from autodist_tpu.strategy.base import PSSynchronizer, Strategy
        if const.ENV.ADT_ELASTIC.val <= 0:
            # Coordinator(max_restarts=...) without the ADT_ELASTIC
            # bring-up: every process joined jax.distributed, whose pinned
            # process set a relaunched worker cannot re-enter — it would
            # churn the budget on confusing join failures
            return ("ADT_ELASTIC was not set at bring-up, so processes "
                    "joined jax.distributed (pinned process set)")
        try:
            strategy = Strategy.deserialize(self._strategy_id)
        except (OSError, ValueError) as e:
            return "strategy %s unreadable (%s)" % (self._strategy_id, e)
        if strategy.graph_config.mesh_shape:
            return "model-parallel mesh axes are collective-lockstep"

        def leaf_nodes(node):
            return node.part_configs or [node]
        for node in strategy.node_config:
            for leaf in leaf_nodes(node):
                sync = leaf.synchronizer or node.synchronizer
                if not isinstance(sync, PSSynchronizer) or sync.sync:
                    return ("var %r is not async host-PS" % node.var_name)
                dest_host = (sync.reduction_destination or "").split(":")[0]
                if dest_host == address:
                    return ("dead worker %s OWNS the PS group of %r — its "
                            "authoritative state died with it"
                            % (address, node.var_name))
        return None

    def stop_watchdog(self):
        """End heartbeat supervision — call when the job finishes cleanly,
        BEFORE workers stop heartbeating, or the watchdog reads their normal
        exit as a failure and aborts a successful run."""
        self._stop_watchdog.set()

    def join(self):
        self.stop_watchdog()
        for t in self._threads:
            if t is not threading.current_thread() and t.is_alive():
                t.join(timeout=5)
