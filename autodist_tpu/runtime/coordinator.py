"""Coordinator — chief launches worker clients and supervises them.

Analog of reference ``autodist/coordinator.py:46-110``: on the chief, launch
*the same user script* (``python sys.argv``) on every worker host with env
``ADT_WORKER=<host>``/``ADT_STRATEGY_ID=<id>`` (the reference's
``AUTODIST_WORKER``/``AUTODIST_STRATEGY_ID``), first copying the serialized
strategy over; a watcher thread per remote process fail-fasts the whole job
(``os._exit(1)``) when any worker dies — the reference's exact supervision
semantics (``coordinator.py:98-110``).
"""
import atexit
import os
import sys
import threading
from typing import List

from autodist_tpu import const
from autodist_tpu.runtime.cluster import Cluster
from autodist_tpu.utils import logging


class Coordinator:
    def __init__(self, strategy, cluster: Cluster,
                 heartbeat_timeout: float = 60.0):
        # a Strategy object, or just its id — the chief-launched flow
        # preallocates the id and launches workers BEFORE the strategy is
        # built (the chief's jax.distributed join blocks until every
        # worker connects, and building requires tracing, which would
        # initialize XLA before the join)
        self._strategy_id = getattr(strategy, "id", strategy)
        self._cluster = cluster
        self._threads: List[threading.Thread] = []
        self._heartbeat_timeout = heartbeat_timeout
        # the cluster owns the service port (it starts the server)
        self._coordsvc_port = cluster.coordsvc_port
        self._stop_watchdog = threading.Event()
        atexit.register(self.join)

    def start_watchdog(self):
        """Heartbeat-based failure detection via the coordination service
        (augments the process-exit watcher): a worker that stops heartbeating
        for ``heartbeat_timeout`` seconds fails the job fast."""
        from autodist_tpu.runtime.coordination import CoordinationClient

        def watch():
            import time as _time
            try:
                client = CoordinationClient("127.0.0.1", self._coordsvc_port)
            except OSError as e:
                logging.warning("watchdog: coordination service unreachable "
                                "on port %d (%s) — heartbeat supervision "
                                "disabled", self._coordsvc_port, e)
                return
            while not self._stop_watchdog.wait(self._heartbeat_timeout / 4):
                try:
                    dead = client.dead_workers(self._heartbeat_timeout)
                except OSError:
                    return
                if dead:
                    logging.error("workers %s missed heartbeats — aborting",
                                  dead)
                    os._exit(1)
        t = threading.Thread(target=watch, daemon=True)
        t.start()
        self._threads.append(t)

    def distribute_strategy(self):
        """Copy the serialized strategy to every worker host (chief-side;
        workers poll for the file by id). In the chief-launched flow this
        runs AFTER the workers are already up — they wait in their
        strategy poll until the file lands."""
        strategy_path = os.path.join(const.DEFAULT_SERIALIZATION_DIR,
                                     self._strategy_id)
        for address in self._cluster.process_addresses:
            if not self._cluster.is_chief(address):
                self._cluster.remote_copy(
                    strategy_path, const.DEFAULT_SERIALIZATION_DIR, address)

    def launch_clients(self, copy_strategy: bool = True):
        """Relaunch this script on every non-chief host."""
        script = os.path.abspath(sys.argv[0])
        argv_rest = " ".join(sys.argv[1:])
        if copy_strategy:
            self.distribute_strategy()
        for address in self._cluster.process_addresses:
            if self._cluster.is_chief(address):
                continue
            env = self._cluster.worker_env(address)
            env[const.ENV.ADT_STRATEGY_ID.name_str] = self._strategy_id
            # propagate the debugging/testing knobs only when explicitly set
            # locally — an empty string would override the worker's default
            # (reference coordinator.py:70-79)
            for e in (const.ENV.ADT_MIN_LOG_LEVEL, const.ENV.ADT_IS_TESTING,
                      const.ENV.ADT_PATCH_OPTAX):
                raw = os.environ.get(e.name_str)
                if raw is not None:
                    env[e.name_str] = raw
            proc = self._cluster.remote_exec(
                "python -u %s %s" % (script, argv_rest), address, env=env)
            if proc is not None:
                self._proc_wait_async(proc, address)
            logging.info("launched worker client on %s (process %d)",
                         address, self._cluster.process_id(address))

    def _proc_wait_async(self, proc, address: str):
        """Fail-fast watcher (reference ``coordinator.py:98-110``). A
        worker death after the job finished cleanly (``stop_watchdog``
        set — e.g. the chief's exit-time terminate SIGTERMing a trailing
        worker) is shutdown, not failure, and must not abort a
        successful run with exit code 1."""
        def watch():
            code = proc.wait()
            if code != 0 and not self._stop_watchdog.is_set():
                logging.error("worker %s exited with code %s — aborting job",
                              address, code)
                os._exit(1)
        t = threading.Thread(target=watch, daemon=True)
        t.start()
        self._threads.append(t)

    def stop_watchdog(self):
        """End heartbeat supervision — call when the job finishes cleanly,
        BEFORE workers stop heartbeating, or the watchdog reads their normal
        exit as a failure and aborts a successful run."""
        self._stop_watchdog.set()

    def join(self):
        self.stop_watchdog()
        for t in self._threads:
            if t is not threading.current_thread() and t.is_alive():
                t.join(timeout=5)
