"""Training health sentinel — in-program anomaly guards + recovery policy.

The computation-plane leg of the resilience story: PR 1 guards the wire
(``runtime/resilience.py``), the checkpoint lifecycle guards the disk
(``checkpoint/integrity.py``), and this module guards the *update* — the
NaN/Inf blowups, loss spikes and silent gradient corruption that turn a
week-long run into garbage while every RPC and every fsync succeeds.

Two halves, split by where the work must happen:

- **In-graph guards** (compiled by ``GraphTransformer`` when a policy is
  active): the distributed step computes a per-step health verdict —
  global gradient norm, any-NaN/Inf over the synced gradients and the
  post-update parameters, loss finiteness — and on a bad verdict the
  update is DISCARDED inside the program (params/opt/compressor state
  carry unchanged through a ``jnp.where`` select; the host-PS push is
  suppressed by the verdict riding the push's own D2H). Detection costs
  zero extra dispatches and zero extra device→host transfers: the
  verdict is a handful of scalars in the existing metrics readback, and
  every input to it is all-reduced, so in a multi-process run every
  worker takes the same branch. The fused ``lax.scan`` path stacks one
  verdict per microstep.

- **Host-side policy** (this module's :class:`Sentinel`, driven by the
  Runner at metrics-readback boundaries): accounts skips against a
  sliding-window budget, tracks an EWMA z-score of the loss for
  sustained spikes the finiteness guards cannot see, and escalates —

  1. **skip** — in-graph (already happened by the time the verdict is
     read); the sentinel only counts it.
  2. **rollback** — past the skip budget, or on a sustained loss spike:
     restore the newest *healthy-stamped* checkpoint through the
     integrity scan, rewind the step counters, and widen the skip budget
     for the replayed window (a deterministic fault re-fires on replay —
     the widened budget is what lets the run skip THROUGH a bounded bad
     region instead of ping-ponging).
  3. **escalate** — a second rollback landing at the same checkpoint
     step halves the effective learning rate (update scaling — exact LR
     semantics for any optax optimizer, applied without recompiling);
     after ``max_rollbacks_per_step`` rollbacks at one step the run
     hard-fails with a typed :class:`TrainingDiverged`.

  While the verdict is bad the sentinel also **quarantines** checkpoint
  saves (the savers consult ``Runner.sentinel_save_veto``), and every
  committed checkpoint carries a ``healthy`` stamp so auto-resume and
  rollback never restore a poisoned state.

See docs/sentinel.md for the knob reference and the chaos harness
(``ADT_GRAD_FAULT_PLAN``) that proves the loop end to end.
"""
import collections
import dataclasses
import json
import math
from typing import Optional

from autodist_tpu import const
from autodist_tpu.telemetry import blackbox
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging


class TrainingDiverged(RuntimeError):
    """Training is unrecoverable under the active :class:`SentinelPolicy`:
    the escalation ladder (skip → rollback → halve LR) is exhausted, or a
    rollback was required and no healthy checkpoint exists. Typed so a
    driver can distinguish a health hard-fail from infrastructure
    errors."""


@dataclasses.dataclass
class SentinelPolicy:
    """Declarative health policy. The in-graph half consumes only
    ``grad_norm_limit`` (a trace-time constant); everything else drives
    the host-side :class:`Sentinel`."""

    # -- skip budget: bad steps discarded in-graph, counted host-side
    max_skips_per_window: int = 3
    window_steps: int = 100          # sliding window, in microsteps
    # -- in-graph guards: skip also when the global grad norm exceeds
    #    this (None = only NaN/Inf gate the in-graph select)
    grad_norm_limit: Optional[float] = None
    # -- sustained loss-spike detection (EWMA z-score over healthy losses)
    spike_zscore: float = 8.0
    ewma_alpha: float = 0.05
    spike_patience: int = 3          # consecutive spiking steps → rollback
    min_history: int = 20            # EWMA warm-up before z-scores count
    # -- escalation ladder
    max_rollbacks_per_step: int = 3  # at ONE checkpoint step; then diverge
    # -- quarantine: veto checkpoint saves while the verdict is bad
    quarantine: bool = True
    enabled: bool = True

    def __post_init__(self):
        for name in ("max_skips_per_window", "window_steps",
                     "spike_patience", "min_history",
                     "max_rollbacks_per_step"):
            if int(getattr(self, name)) < 1:
                raise ValueError("SentinelPolicy.%s must be >= 1, got %r"
                                 % (name, getattr(self, name)))
        if not (0.0 < self.ewma_alpha <= 1.0):
            raise ValueError("SentinelPolicy.ewma_alpha must be in (0, 1], "
                             "got %r" % (self.ewma_alpha,))

    @classmethod
    def from_env(cls) -> Optional["SentinelPolicy"]:
        """Policy from ``ADT_SENTINEL``: unset/"0" → None (off), "1" →
        defaults, a JSON object → keyword overrides."""
        raw = const.ENV.ADT_SENTINEL.val.strip()
        if raw in ("", "0", "off", "false", "False"):
            return None
        if raw.startswith("{"):
            return cls(**json.loads(raw))
        return cls()


def resolve_policy(sentinel) -> Optional[SentinelPolicy]:
    """One resolution rule shared by AutoDist and Runner: ``None`` defers
    to the env (``ADT_SENTINEL``), ``False`` forces off, ``True`` is the
    default policy, a :class:`SentinelPolicy` is used as-is (respecting
    its own ``enabled`` flag)."""
    if sentinel is None:
        policy = SentinelPolicy.from_env()
    elif sentinel is False:
        return None
    elif sentinel is True:
        policy = SentinelPolicy()
    elif isinstance(sentinel, SentinelPolicy):
        policy = sentinel
    else:
        raise TypeError("sentinel must be None, a bool, or a "
                        "SentinelPolicy; got %r" % (sentinel,))
    if policy is not None and not policy.enabled:
        return None
    return policy


class Sentinel:
    """Host-side policy engine. The Runner feeds it one metrics dict per
    MICROSTEP (at readback boundaries, in step order) via
    :meth:`observe`, and calls :meth:`maybe_act` at safe points (before a
    dispatch, after a readback) — ``observe`` only updates state, so a
    rollback never fires reentrantly from inside a metrics
    materialization."""

    def __init__(self, policy: SentinelPolicy, runner):
        self.policy = policy
        self._runner = runner
        self._micro = 0                 # microsteps observed
        self._skip_steps = collections.deque()  # micro indexes of skips
        self.skips = 0
        self.rollbacks = 0
        self.lr_halvings = 0
        self.last_grad_norm: Optional[float] = None
        self._verdict_bad = False       # last observed in-graph verdict
        self._pending_rollback: Optional[str] = None
        self._rollbacks_at = {}         # restored step -> rollback count
        self._budget_mult = 1           # widened after each rollback
        self.lr_scale = 1.0
        # EWMA of the loss over HEALTHY observations only (a bad step's
        # loss — possibly NaN — must not poison the baseline)
        self._ewma_mean: Optional[float] = None
        self._ewma_var = 0.0
        self._ewma_n = 0
        self._spike_streak = 0
        self._saver = None              # fit() attaches its saver

    # ------------------------------------------------------------ observe

    def observe(self, metrics) -> None:
        """Ingest one microstep's host metrics (readback boundary)."""
        self._micro += 1
        verdict = metrics.get("sentinel") if hasattr(metrics, "get") else None
        loss = metrics.get("loss") if hasattr(metrics, "get") else None
        loss = float(loss) if loss is not None else None
        if verdict is not None:
            self._observe_guarded(verdict, loss)
        elif loss is not None:
            # guards not compiled (step_fn mode / ADT420): loss-only
            # monitoring — a nonfinite loss cannot be skipped in-graph,
            # so it goes straight to the rollback ladder
            if not math.isfinite(loss):
                tel.counter_add("sentinel.nan_steps")
                self._verdict_bad = True
                self._pend("nonfinite loss (unguarded program)")
            else:
                self._verdict_bad = False
                self._observe_loss(loss)

    def _observe_guarded(self, verdict, loss) -> None:
        ok = bool(int(verdict["ok"]))
        self.last_grad_norm = float(verdict["grad_norm"])
        if math.isfinite(self.last_grad_norm):
            tel.gauge_set("sentinel.grad_norm", self.last_grad_norm)
        if ok:
            self._verdict_bad = False
            if loss is not None and math.isfinite(loss):
                self._observe_loss(loss)
            return
        self._verdict_bad = True
        self.skips += 1
        tel.counter_add("sentinel.skips")
        if float(verdict.get("bad_grads", 0)) > 0 \
                or float(verdict.get("bad_params", 0)) > 0:
            tel.counter_add("sentinel.nan_steps")
        tel.instant("sentinel.skip", "sentinel", micro=self._micro,
                    grad_norm=self.last_grad_norm)
        # the black box keeps every BAD verdict (bounded deque): a
        # postmortem reads the health trajectory leading into the fatal
        # verdict even when tracing was off
        blackbox.record("sentinel.verdict", ok=False, micro=self._micro,
                        grad_norm=self.last_grad_norm,
                        bad_grads=float(verdict.get("bad_grads", 0)),
                        bad_params=float(verdict.get("bad_params", 0)))
        self._skip_steps.append(self._micro)
        horizon = self._micro - self.policy.window_steps
        while self._skip_steps and self._skip_steps[0] <= horizon:
            self._skip_steps.popleft()
        budget = self.policy.max_skips_per_window * self._budget_mult
        logging.warning(
            "sentinel: unhealthy step discarded in-graph (grad_norm=%.3g, "
            "bad_grads=%s, bad_params=%s) — %d/%d skips in window",
            self.last_grad_norm, verdict.get("bad_grads"),
            verdict.get("bad_params"), len(self._skip_steps), budget)
        if len(self._skip_steps) > budget:
            self._pend("skip budget exhausted (%d skips in the last %d "
                       "microsteps, budget %d)"
                       % (len(self._skip_steps), self.policy.window_steps,
                          budget))

    def _observe_loss(self, loss: float) -> None:
        p = self.policy
        if self._ewma_mean is None:
            self._ewma_mean, self._ewma_n = loss, 1
            return
        std = math.sqrt(max(self._ewma_var, 0.0))
        z = abs(loss - self._ewma_mean) / (std + 1e-12)
        if self._ewma_n >= p.min_history and z > p.spike_zscore:
            self._spike_streak += 1
            logging.warning("sentinel: loss %.6g is %.1f sigma from the "
                            "EWMA baseline %.6g (streak %d/%d)", loss, z,
                            self._ewma_mean, self._spike_streak,
                            p.spike_patience)
            if self._spike_streak >= p.spike_patience:
                self._verdict_bad = True  # quarantine saves too
                self._pend("sustained loss spike (%d steps > %.1f sigma)"
                           % (self._spike_streak, p.spike_zscore))
            return  # a spiking loss must not drag the baseline up
        self._spike_streak = 0
        delta = loss - self._ewma_mean
        self._ewma_mean += p.ewma_alpha * delta
        self._ewma_var = ((1.0 - p.ewma_alpha)
                          * (self._ewma_var + p.ewma_alpha * delta * delta))
        self._ewma_n += 1

    def _pend(self, reason: str) -> None:
        if self._pending_rollback is None:
            self._pending_rollback = reason
            blackbox.record("sentinel.rollback_pending", reason=reason,
                            micro=self._micro)

    # ---------------------------------------------------------------- act

    @property
    def quarantined(self) -> bool:
        """True while checkpoint saves must be vetoed: the last verdict
        was bad, or a rollback is pending."""
        return self.policy.quarantine and (
            self._verdict_bad or self._pending_rollback is not None)

    def healthy(self) -> bool:
        """The stamp a checkpoint committed NOW would carry."""
        return not (self._verdict_bad or self._pending_rollback is not None)

    def attach_saver(self, saver) -> None:
        if saver is not None:
            self._saver = saver

    def maybe_act(self) -> None:
        """Perform a pending rollback (or raise :class:`TrainingDiverged`
        when the ladder is exhausted). Called by the Runner at safe
        points only — never from inside a metrics materialization."""
        if self._pending_rollback is None:
            return
        reason, self._pending_rollback = self._pending_rollback, None
        self._rollback(reason)

    def _ckpt_dir(self) -> str:
        if self._saver is not None:
            return self._saver.directory
        return const.ENV.ADT_CKPT_DIR.val

    def _rollback(self, reason: str) -> None:
        from autodist_tpu.checkpoint import latest_checkpoint
        directory = self._ckpt_dir()
        with tel.span("sentinel.rollback", "sentinel", reason=reason):
            if self._saver is not None:
                # land any in-flight async write so the newest committed
                # (healthy) checkpoint is visible to the scan
                self._saver.wait()
            step, saver = latest_checkpoint(directory)
            if saver is None:
                self._diverge("sentinel rollback required (%s) but no "
                              "healthy committed checkpoint exists in %s "
                              "— enable periodic saves "
                              "(fit(save_every=...)) to make rollback "
                              "possible" % (reason, directory))
            count = self._rollbacks_at.get(step, 0) + 1
            self._rollbacks_at[step] = count
            if count > self.policy.max_rollbacks_per_step:
                self._diverge("sentinel rolled back to step %d %d times "
                              "(%s) — the escalation ladder (skip → "
                              "rollback → halve LR) is exhausted"
                              % (step, count - 1, reason))
            logging.warning("sentinel: ROLLBACK #%d to checkpoint step %d "
                            "(%s)", count, step, reason)
            blackbox.record("sentinel.rollback", step=int(step),
                            count=count, reason=reason)
            _, restored_step = saver.restore(self._runner)
            # rewind the pacing/mirror protocols to the restored step and
            # widen the skip budget: a deterministic fault re-fires on
            # replay, and the widened window is what lets the run skip
            # through a bounded bad region instead of ping-ponging
            self._runner._step_count = int(restored_step)
            self._budget_mult = 2 ** count
            self._skip_steps.clear()
            self._spike_streak = 0
            self._verdict_bad = False
            if count >= 2:
                self._halve_lr()
            self.rollbacks += 1
            tel.counter_add("sentinel.rollbacks")
        # the completed rollback IS a black-box trigger: a run that later
        # dies (or quietly mistrains) leaves the what/when/why on disk
        blackbox.dump("sentinel rollback #%d" % self.rollbacks)

    def _diverge(self, message: str):
        """Record the fatal verdict + dump the black box, then raise the
        typed hard-fail — the dump is the postmortem artifact the run
        leaves behind (events carry the rollback/verdict trail; the span
        tail carries the last ``sentinel.rollback`` span when tracing
        was on)."""
        blackbox.record("sentinel.diverged", reason=message)
        blackbox.dump("training_diverged")
        raise TrainingDiverged(message)

    def _halve_lr(self) -> None:
        """Escalation: halve the EFFECTIVE learning rate by scaling the
        optimizer's updates — exact LR semantics for any optax transform
        whose update is linear in lr (sgd, adam, ...), applied without
        recompiling: the scale rides the sync_state (device vars, read
        in-graph) and ``PSStore.update_scale`` (host-applied PS vars)."""
        import numpy as np

        self.lr_scale *= 0.5
        self.lr_halvings += 1
        tel.counter_add("sentinel.lr_halvings")
        logging.warning("sentinel: repeated rollback at the same step — "
                        "halving effective LR to %.4gx", self.lr_scale)
        runner = self._runner
        dstep = runner.distributed_step
        store = getattr(dstep, "ps_store", None)
        if store is not None:
            store.update_scale = self.lr_scale
        state = runner.state
        sync = dict(state.sync_state) if isinstance(state.sync_state,
                                                    dict) else None
        if sync is None or "sentinel" not in sync:
            if store is None:
                logging.warning(
                    "sentinel: lowered program carries no lr_scale input "
                    "(guards not compiled?) — LR escalation is a no-op")
            return
        n = int(getattr(dstep.mesh, "size", 1))
        placed = dstep.place_sync_state(
            {"lr_scale": np.full((n,), self.lr_scale, np.float32)})
        sync["sentinel"] = placed
        runner.state = state.replace(sync_state=sync)

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        """The stable ``step_stats()['sentinel']`` sub-dict."""
        return {"skips": self.skips, "rollbacks": self.rollbacks,
                "last_grad_norm": self.last_grad_norm,
                "quarantined": self.quarantined}
