"""Distributed-runtime bring-up for one process.

Analog of reference ``autodist/utils/server_starter.py:50-76``: where the
reference runs a standalone ``tf.distribute.Server`` per node (with NCCL
collectives and a group leader), on TPU every worker process joins the JAX
distributed runtime directly — process 0 hosts the coordination service
(the group-leader role, reference ``const.py:52``), and XLA's ICI/DCN
collectives replace the gRPC/NCCL data plane. Stale-server cleanup
(reference ``:29-46``) maps to clearing a crashed coordination service's
port before rebinding.
"""
import os
import signal
import subprocess

from autodist_tpu import const
from autodist_tpu.utils import logging

_INITIALIZED = False
_ELASTIC_STARTED = False  # elastic bring-up done (no jax.distributed join)


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int):
    """Join the JAX distributed runtime (idempotent)."""
    global _INITIALIZED
    if _INITIALIZED or num_processes <= 1:
        return
    import jax
    try:
        from jax._src import xla_bridge
        already = xla_bridge.backends_are_initialized()
    except (ImportError, AttributeError):
        already = False  # private probe unavailable; initialize() still fails loudly
    if already:
        raise RuntimeError(
            "the XLA backend is already initialized, so this process cannot "
            "join the %d-process distributed runtime. In multi-process jobs, "
            "construct AutoDist() (or call server_starter.init_distributed) "
            "BEFORE any JAX computation — including jnp array creation for "
            "model parameters." % num_processes)
    logging.info("jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
                 coordinator_address, num_processes, process_id)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _INITIALIZED = True


def reinit_distributed(coordinator_address: str, num_processes: int,
                       process_id: int):
    """Tear down this process's jax.distributed membership and join a NEW
    process set — the in-run elastic reconfiguration path
    (``runtime/elastic.rejoin_process_set``). The old runtime's device
    buffers and cached backends are invalid across this call; callers
    hold a host-side state snapshot and re-place it afterwards.

    Raises ``RuntimeError`` when the runtime cannot be re-initialized in
    this process (older jax backends without a clean shutdown path) — the
    coordinator's ack-timeout escalation then falls back to the PR 8
    whole-job re-exec, which achieves the same membership change by
    replacing the process image."""
    global _INITIALIZED
    import jax
    if _INITIALIZED:
        try:
            jax.distributed.shutdown()
        except Exception as e:  # noqa: BLE001 — a dead peer can fail the
            # shutdown barrier; the local teardown below is what matters
            logging.warning("jax.distributed.shutdown during elastic "
                            "rejoin: %s (continuing)", e)
        _INITIALIZED = False
    # drop the cached XLA backends so the next device query builds
    # clients for the NEW world (public clear_backends was removed; the
    # private hook is version-gated and failure here must be loud — a
    # stale backend would silently run collectives over the dead mesh)
    try:
        from jax._src import xla_bridge
        xla_bridge._clear_backends()
        jax.clear_caches()
    except Exception as e:  # noqa: BLE001
        raise RuntimeError(
            "cannot re-initialize the XLA backend in-process (%s); "
            "in-run elastic reconfiguration is unavailable on this jax "
            "build — falling back to whole-job restart" % e) from e
    if num_processes <= 1:
        logging.warning("elastic rejoin: single survivor — local backend "
                        "only (no jax.distributed)")
        return
    logging.warning("elastic rejoin: jax.distributed.initialize(%s, "
                    "num_processes=%d, process_id=%d)",
                    coordinator_address, num_processes, process_id)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _INITIALIZED = True


def initialized() -> bool:
    """True once this process's distributed bring-up has happened — a
    jax.distributed join, or an elastic bring-up (which deliberately has
    none). Guards AutoDist's chief-launched flow against re-entry: a
    second AutoDist() in the same process must not relaunch workers."""
    return _INITIALIZED or _ELASTIC_STARTED


def mark_elastic_started():
    global _ELASTIC_STARTED
    _ELASTIC_STARTED = True


def maybe_init_distributed():
    """Worker-side auto-join from the env the Coordinator set
    (chief side passes explicit args via Cluster.start). Elastic jobs
    never join: jax.distributed pins a fixed process set for the job's
    lifetime, while elastic async PS needs workers to come and go — they
    couple through the coordination service alone."""
    if const.ENV.ADT_ELASTIC.val > 0 and not const.ENV.ADT_ELASTIC_SYNC.val:
        if const.ENV.ADT_EXTERNAL_LAUNCH.val:
            # external launchers own process lifecycles (no Coordinator to
            # relaunch anything) AND their strategy handoff is a collective
            # broadcast that requires the jax.distributed join — silently
            # skipping it here would wedge the handoff confusingly
            raise ValueError(
                "ADT_ELASTIC requires the chief-launched flow; externally-"
                "launched jobs (ADT_EXTERNAL_LAUNCH) restart workers "
                "through their own launcher instead")
        logging.info("elastic mode: skipping jax.distributed join "
                     "(process coupling is via the parameter service)")
        return
    if const.ENV.ADT_ELASTIC_INRUN.val and const.is_worker():
        # in-run elastic worker bring-up: a relaunched/hot-spare worker
        # whose roster no longer includes it must NOT join the original
        # process set (stale env) — it announces itself and joins the
        # epoch that admits it (runtime/elastic.py grow-on-join)
        from autodist_tpu.runtime import elastic
        worker = const.ENV.ADT_WORKER.val
        info = elastic.wait_for_admission(worker)
        if info is not None:
            epoch, roster = info
            layout = elastic.roster_layout(
                roster, const.ENV.ADT_COORDINATOR_ADDR.val.split(":")[0]
                or roster[0])
            if epoch > 1:
                membership = elastic.install(
                    elastic.Membership(worker, epoch, roster))
                membership.joined_late = True
                # participate in the survivors' reconfiguration barrier:
                # its count spans the WHOLE new roster, joiner included
                membership.barrier_reconf(epoch, len(roster))
            os.environ[const.ENV.ADT_NUM_PROCESSES.name_str] = (
                str(len(layout)))
            os.environ[const.ENV.ADT_PROCESS_ID.name_str] = (
                str(layout.index(worker)))
            init_distributed(elastic.epoch_coordinator_address(epoch),
                             len(layout), layout.index(worker))
            return
    addr = const.ENV.ADT_COORDINATOR_ADDR.val
    n = const.ENV.ADT_NUM_PROCESSES.val
    if addr and n > 1:
        init_distributed(addr, n, const.ENV.ADT_PROCESS_ID.val)


def broadcast_bytes(payload=None) -> bytes:
    """Collective broadcast of a byte string from process 0 to every process.

    The strategy handoff for externally-launched jobs (all processes started
    simultaneously): unlike a shared filesystem, the job's own collective
    cannot deliver bytes from a *previous* run, so workers can never load a
    stale strategy. Must be called by ALL processes; only process 0's
    ``payload`` is used (others pass None).
    """
    import jax
    import numpy as np
    from jax.experimental import multihost_utils
    is_src = jax.process_index() == 0
    if is_src and payload is None:
        raise ValueError("process 0 must provide the payload")
    length = int(multihost_utils.broadcast_one_to_all(
        np.int64(len(payload) if is_src else 0)))
    buf = (np.frombuffer(payload, np.uint8) if is_src
           else np.zeros(length, np.uint8))
    return bytes(np.asarray(multihost_utils.broadcast_one_to_all(buf)))


def clean_stale_servers(script_name: str = "server_starter"):
    """Kill leftover processes from a crashed previous run
    (reference ``server_starter.py:29-46``)."""
    me = os.getpid()
    try:
        out = subprocess.run(["pgrep", "-f", script_name], check=False,
                             capture_output=True, text=True).stdout
    except FileNotFoundError:
        return
    for line in out.split():
        pid = int(line)
        if pid != me:
            try:
                os.kill(pid, signal.SIGTERM)
                logging.info("killed stale process %d", pid)
            except (ProcessLookupError, PermissionError):
                pass


def main():  # CLI parity with the reference's per-node starter
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--coordinator_address", required=True)
    parser.add_argument("--num_processes", type=int, required=True)
    parser.add_argument("--process_id", type=int, required=True)
    args = parser.parse_args()
    clean_stale_servers()
    init_distributed(args.coordinator_address, args.num_processes, args.process_id)
    signal.pause()  # join() forever, like the reference server


if __name__ == "__main__":
    main()
