"""Distributed-runtime bring-up for one process.

Analog of reference ``autodist/utils/server_starter.py:50-76``: where the
reference runs a standalone ``tf.distribute.Server`` per node (with NCCL
collectives and a group leader), on TPU every worker process joins the JAX
distributed runtime directly — process 0 hosts the coordination service
(the group-leader role, reference ``const.py:52``), and XLA's ICI/DCN
collectives replace the gRPC/NCCL data plane. Stale-server cleanup
(reference ``:29-46``) maps to clearing a crashed coordination service's
port before rebinding.
"""
import os
import signal
import subprocess

from autodist_tpu import const
from autodist_tpu.utils import logging

_INITIALIZED = False


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int):
    """Join the JAX distributed runtime (idempotent)."""
    global _INITIALIZED
    if _INITIALIZED or num_processes <= 1:
        return
    import jax
    try:
        from jax._src import xla_bridge
        already = xla_bridge.backends_are_initialized()
    except (ImportError, AttributeError):
        already = False  # private probe unavailable; initialize() still fails loudly
    if already:
        raise RuntimeError(
            "the XLA backend is already initialized, so this process cannot "
            "join the %d-process distributed runtime. In multi-process jobs, "
            "construct AutoDist() (or call server_starter.init_distributed) "
            "BEFORE any JAX computation — including jnp array creation for "
            "model parameters." % num_processes)
    logging.info("jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
                 coordinator_address, num_processes, process_id)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _INITIALIZED = True


def initialized() -> bool:
    return _INITIALIZED


def maybe_init_distributed():
    """Worker-side auto-join from the env the Coordinator set
    (chief side passes explicit args via Cluster.start)."""
    addr = const.ENV.ADT_COORDINATOR_ADDR.val
    n = const.ENV.ADT_NUM_PROCESSES.val
    if addr and n > 1:
        init_distributed(addr, n, const.ENV.ADT_PROCESS_ID.val)


def broadcast_bytes(payload=None) -> bytes:
    """Collective broadcast of a byte string from process 0 to every process.

    The strategy handoff for externally-launched jobs (all processes started
    simultaneously): unlike a shared filesystem, the job's own collective
    cannot deliver bytes from a *previous* run, so workers can never load a
    stale strategy. Must be called by ALL processes; only process 0's
    ``payload`` is used (others pass None).
    """
    import jax
    import numpy as np
    from jax.experimental import multihost_utils
    is_src = jax.process_index() == 0
    if is_src and payload is None:
        raise ValueError("process 0 must provide the payload")
    length = int(multihost_utils.broadcast_one_to_all(
        np.int64(len(payload) if is_src else 0)))
    buf = (np.frombuffer(payload, np.uint8) if is_src
           else np.zeros(length, np.uint8))
    return bytes(np.asarray(multihost_utils.broadcast_one_to_all(buf)))


def clean_stale_servers(script_name: str = "server_starter"):
    """Kill leftover processes from a crashed previous run
    (reference ``server_starter.py:29-46``)."""
    me = os.getpid()
    try:
        out = subprocess.run(["pgrep", "-f", script_name], check=False,
                             capture_output=True, text=True).stdout
    except FileNotFoundError:
        return
    for line in out.split():
        pid = int(line)
        if pid != me:
            try:
                os.kill(pid, signal.SIGTERM)
                logging.info("killed stale process %d", pid)
            except (ProcessLookupError, PermissionError):
                pass


def main():  # CLI parity with the reference's per-node starter
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--coordinator_address", required=True)
    parser.add_argument("--num_processes", type=int, required=True)
    parser.add_argument("--process_id", type=int, required=True)
    args = parser.parse_args()
    clean_stale_servers()
    init_distributed(args.coordinator_address, args.num_processes, args.process_id)
    signal.pause()  # join() forever, like the reference server


if __name__ == "__main__":
    main()
