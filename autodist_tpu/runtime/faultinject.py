"""Deterministic fault injection for the coordination-service wire.

:class:`FaultyProxy` is a TCP proxy that sits between coordination clients
and the real ``coordination_service`` process and executes a seeded,
declarative **fault plan** — faults traverse the real wire path (real
sockets, real partial reads, real RSTs), not mocks, so the chaos suite
(``tests/test_faults.py``) exercises exactly the failure surface
production sees.

The plan is JSON, from the ``ADT_FAULT_PLAN`` env var (inline JSON, or
``@/path/to/plan.json``) or passed directly::

    {
      "seed": 1234,
      "faults": [
        {"op": "delay",    "match": "QPUSHB", "nth": 2, "delay_s": 0.5},
        {"op": "reset",    "match": "*",      "nth": 5, "repeat": true},
        {"op": "truncate", "match": "BGETB",  "nth": 1, "bytes": 64},
        {"op": "restart",  "at_step": 3}
      ]
    }

Fault classes (``op``):

- ``delay``    — hold the matched request for ``delay_s`` seconds before
  forwarding (an RPC slower than the client deadline).
- ``reset``    — hard-close the client connection (SO_LINGER 0 => TCP RST)
  the moment the matched request completes parsing; the request is
  **dropped before forwarding**, modeling a send that never reached the
  service. With ``"when": "after"`` the request IS forwarded and the
  reply relayed is cut instead — the *ambiguous* drop (applied, reply
  lost) the idempotency tokens exist for.
- ``truncate`` — forward the matched request, relay at most ``bytes`` of
  the response, then reset — a blob cut mid-payload.
- ``restart``  — when a ``STEP`` command with step >= ``at_step`` passes
  through, invoke the proxy's ``restart_fn`` (kill + relaunch the real
  service); models a control-plane crash mid-run.
- ``preempt``  — advance-notice eviction of a supervised process: a real
  SIGTERM to ``pid`` (or the proxy's ``preempt_pid``) when the rule
  fires, then a real SIGKILL ``deadline_s`` later — the spot-VM /
  maintenance-event timing the preemption plane
  (``runtime/preemption.py``) must beat.

Matching: ``match`` prefix-matches the command word (``"*"`` = any
non-PING command; PING is the liveness probe both sides use and is never
faulted so tests converge). ``nth`` fires on the n-th matching RPC
(1-based, counted across all proxied connections); ``repeat`` re-fires
every ``nth`` matches; ``prob`` fires with seeded probability instead.
Determinism: one global, locked RPC counter and one ``random.Random``
seeded from the plan — the same plan against the same client sequence
injects the same faults.

The proxy parses just enough of the protocol to find RPC boundaries (the
newline-delimited headers plus the length-prefixed binary payloads of
BPUTB/QPUSHB) — it never interprets or rewrites payloads.
"""
import json
import os
import random
import signal
import socket
import struct
import threading
import time
from typing import Callable, List, Optional

from autodist_tpu import const
from autodist_tpu.utils import logging

# commands whose header declares a raw payload length in this 0-based arg
_BINARY_LEN_ARG = {"BPUTB": 3, "QPUSHB": 2}


class FaultRule:
    """One declarative fault. See the module docstring for fields."""

    def __init__(self, spec: dict):
        self.op = spec["op"]
        if self.op not in ("delay", "reset", "truncate", "restart",
                           "partition", "preempt"):
            raise ValueError("unknown fault op %r" % self.op)
        self.match = spec.get("match", "*")
        self.nth = int(spec.get("nth", 1))
        self.repeat = bool(spec.get("repeat", False))
        self.prob = spec.get("prob")
        self.delay_s = float(spec.get("delay_s", 0.0))
        # partition: how long the proxy blackholes ALL traffic once this
        # rule fires (the zombie-revival harness — see FaultyProxy)
        self.duration_s = float(spec.get("duration_s", 0.0))
        self.bytes = int(spec.get("bytes", 0))
        self.when = spec.get("when", "before")
        self.at_step = spec.get("at_step")
        # preempt: advance-notice eviction of the target process — a REAL
        # SIGTERM the moment this rule fires, then a REAL SIGKILL
        # ``deadline_s`` later (the spot-VM / maintenance-event timing;
        # see deliver_preemption). ``pid`` defaults to the proxy's
        # ``preempt_pid`` (the training subprocess a chaos harness runs).
        self.deadline_s = spec.get("deadline_s")
        self.pid = spec.get("pid")
        self._matched = 0
        self._spent = False

    def _matches_cmd(self, cmd: str) -> bool:
        if cmd == "PING":
            return False
        return self.match == "*" or cmd.startswith(self.match)

    def should_fire(self, cmd: str, step_arg: Optional[int],
                    rng) -> bool:
        """Called under the plan lock, once per parsed RPC."""
        if self._spent:
            return False
        if self.op == "restart":
            if self.at_step is None or cmd != "STEP" or step_arg is None:
                return False
            if step_arg >= int(self.at_step):
                self._spent = True  # one restart per rule
                return True
            return False
        if not self._matches_cmd(cmd):
            return False
        if self.prob is not None:
            return rng.random() < float(self.prob)
        self._matched += 1
        if self._matched >= self.nth:
            if self.repeat:
                self._matched = 0
            else:
                self._spent = True
            return True
        return False


class FaultPlan:
    """The parsed ``ADT_FAULT_PLAN``: rules + the seeded RNG + counters."""

    def __init__(self, spec: Optional[dict] = None):
        spec = spec or {}
        self.seed = int(spec.get("seed", 0))
        self.rules: List[FaultRule] = [FaultRule(r)
                                       for r in spec.get("faults", ())]
        self.rng = random.Random(self.seed)
        self.lock = threading.Lock()
        self.injected: List[str] = []  # audit log: what fired, in order
        # network-partition window (monotonic deadline): while set, every
        # proxied RPC — on every connection — is HELD until the window
        # heals, then delivered late. This is the zombie-revival fault:
        # the partitioned worker is alive but silent (declared dead,
        # fenced out of the next epoch), and its delayed writes arrive
        # only after its replacement took over — exactly what the
        # epoch fence must reject.
        self.partition_until = 0.0

    @classmethod
    def from_env(cls) -> "FaultPlan":
        raw = const.ENV.ADT_FAULT_PLAN.val
        if not raw:
            return cls()
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        elif os.path.exists(raw):
            with open(raw) as f:
                raw = f.read()
        return cls(json.loads(raw))

    def decide(self, cmd: str, step_arg: Optional[int]) -> List[FaultRule]:
        """All rules that fire for this RPC (deterministic order)."""
        with self.lock:
            fired = [r for r in self.rules
                     if r.should_fire(cmd, step_arg, self.rng)]
            for r in fired:
                self.injected.append("%s:%s" % (r.op, cmd))
            return fired


class _ConnState:
    """Client->upstream stream parser state for one proxied connection."""

    def __init__(self):
        self.buf = b""
        self.bin_need = 0      # payload bytes still owed to the last header
        self.pending = b""     # complete RPC bytes awaiting forwarding


class FaultyProxy:
    """TCP proxy executing a :class:`FaultPlan` on the real wire path.

    ``restart_fn`` (optional) is invoked for ``restart`` faults — it must
    bounce the REAL service (e.g. ``server.stop(); server.start()``); the
    proxy keeps its own listening port, so clients reconnect through the
    same address and find the fresh service."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 listen_port: int = 0, plan: Optional[FaultPlan] = None,
                 restart_fn: Optional[Callable[[], None]] = None,
                 preempt_pid: Optional[int] = None):
        self._upstream = (upstream_host, upstream_port)
        self._plan = plan if plan is not None else FaultPlan.from_env()
        self._restart_fn = restart_fn
        # target for "preempt" rules without an explicit pid — the
        # training subprocess a chaos harness supervises
        self.preempt_pid = preempt_pid
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("127.0.0.1", listen_port))
        self._listen.listen(128)
        self.port = self._listen.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns_lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="adt-faultproxy", daemon=True)

    @property
    def plan(self) -> FaultPlan:
        return self._plan

    def start(self) -> "FaultyProxy":
        self._accept_thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._listen.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for s in conns:
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------ internals

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                client, _ = self._listen.accept()
            except OSError:
                return  # listener closed
            t = threading.Thread(target=self._serve, args=(client,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _track(self, sock: socket.socket):
        with self._conns_lock:
            self._conns.append(sock)

    @staticmethod
    def _hard_reset(sock: socket.socket):
        """Close with SO_LINGER 0: the peer sees a TCP RST (ECONNRESET),
        the rudest real-world failure mode — not a clean FIN."""
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _serve(self, client: socket.socket):
        self._track(client)
        try:
            upstream = socket.create_connection(self._upstream, timeout=5)
        except OSError:
            self._hard_reset(client)
            return
        self._track(upstream)
        # reply pump: upstream -> client, with optional truncation budget.
        # budget[0] is None (no cap) or bytes still allowed through.
        budget = [None]
        budget_lock = threading.Lock()
        done = threading.Event()

        def pump_replies():
            try:
                while True:
                    data = upstream.recv(262144)
                    if not data:
                        break
                    with budget_lock:
                        cap = budget[0]
                        if cap is not None:
                            data = data[:cap]
                            budget[0] = cap - len(data)
                    if data:
                        client.sendall(data)
                    with budget_lock:
                        if budget[0] is not None and budget[0] <= 0:
                            break  # truncation: cut the reply mid-payload
            except OSError:
                pass
            finally:
                done.set()
                with budget_lock:
                    faulted = budget[0] is not None
                if faulted:
                    # a truncate/reset fault engaged: the cut must look
                    # like the violent failure it models (TCP RST)
                    self._hard_reset(client)
                else:
                    # fault-free upstream close (e.g. SHUTDOWN): relay a
                    # clean FIN — the proxy must never inject resets the
                    # plan did not declare
                    try:
                        client.close()
                    except OSError:
                        pass
                try:
                    upstream.close()
                except OSError:
                    pass

        rt = threading.Thread(target=pump_replies, daemon=True)
        rt.start()
        state = _ConnState()
        try:
            while not done.is_set():
                data = client.recv(262144)
                if not data:
                    break
                state.buf += data
                if not self._drain_rpcs(state, client, upstream,
                                        budget, budget_lock):
                    return  # connection was reset by a fault
        except OSError:
            pass
        finally:
            try:
                upstream.shutdown(socket.SHUT_WR)  # EOF propagates upstream
            except OSError:
                pass
            done.wait(timeout=5)
            try:
                client.close()
            except OSError:
                pass

    def _drain_rpcs(self, state: _ConnState, client, upstream,
                    budget, budget_lock) -> bool:
        """Carve complete RPCs out of ``state.buf``, applying faults at
        each boundary. Returns False when a fault reset the connection."""
        while True:
            if state.bin_need > 0:
                take = min(state.bin_need, len(state.buf))
                state.pending += state.buf[:take]
                state.buf = state.buf[take:]
                state.bin_need -= take
                if state.bin_need > 0:
                    return True  # payload incomplete: wait for more bytes
                if not self._dispatch(state, client, upstream,
                                      budget, budget_lock):
                    return False
                continue
            pos = state.buf.find(b"\n")
            if pos < 0:
                return True
            header = state.buf[:pos + 1]
            state.buf = state.buf[pos + 1:]
            parts = header.decode("latin-1").split()
            state.pending += header
            need_arg = _BINARY_LEN_ARG.get(parts[0] if parts else "")
            if need_arg is not None and len(parts) > need_arg:
                try:
                    state.bin_need = max(0, int(parts[need_arg]))
                except ValueError:
                    state.bin_need = 0  # server will reject; just forward
                if state.bin_need > 0:
                    continue  # accumulate the payload first
            if not self._dispatch(state, client, upstream,
                                  budget, budget_lock):
                return False

    def _dispatch(self, state: _ConnState, client, upstream,
                  budget, budget_lock) -> bool:
        """One complete RPC is in ``state.pending``: decide faults, then
        forward (or not). Returns False when the connection was reset."""
        rpc, state.pending = state.pending, b""
        parts = rpc.split(b"\n", 1)[0].decode("latin-1").split()
        cmd = parts[0] if parts else ""
        step_arg = None
        if cmd == "STEP" and len(parts) >= 3:
            try:
                step_arg = int(parts[2])
            except ValueError:
                pass
        fired = self._plan.decide(cmd, step_arg)
        reset_after = False
        for rule in fired:
            if rule.op == "delay":
                logging.info("faultinject: delaying %s by %.3fs",
                             cmd, rule.delay_s)
                time.sleep(rule.delay_s)
            elif rule.op == "reset" and rule.when == "before":
                # drop the request entirely: it never reached the service
                logging.info("faultinject: reset (before) on %s", cmd)
                self._hard_reset(client)
                self._hard_reset(upstream)
                return False
            elif rule.op == "reset":
                # cut the reply path BEFORE forwarding: the request must
                # reach the service, the reply must never reach the client
                # — the ambiguous drop, with no race against the pump
                with budget_lock:
                    budget[0] = 0
                reset_after = True
            elif rule.op == "truncate":
                with budget_lock:
                    budget[0] = rule.bytes
                logging.info("faultinject: truncating reply of %s to %d "
                             "bytes", cmd, rule.bytes)
            elif rule.op == "partition":
                with self._plan.lock:
                    self._plan.partition_until = (time.monotonic()
                                                  + rule.duration_s)
                logging.warning("faultinject: PARTITION for %.1fs starting "
                                "at %s", rule.duration_s, cmd)
            elif rule.op == "preempt":
                pid = rule.pid if rule.pid is not None else self.preempt_pid
                if pid is None:
                    logging.error(
                        "faultinject: preempt rule fired at %s but no "
                        "target pid is configured (rule 'pid' or "
                        "FaultyProxy(preempt_pid=)) — skipping", cmd)
                else:
                    deliver_preemption(int(pid), rule.deadline_s,
                                       reason="faultinject@%s" % cmd)
            elif rule.op == "restart" and self._restart_fn is not None:
                logging.warning("faultinject: restarting service at %s %s",
                                cmd, step_arg)
                self._restart_fn()
        with self._plan.lock:
            hold = self._plan.partition_until - time.monotonic()
        if hold > 0:
            # the partition window: hold (don't drop) — delivery resumes
            # the instant the partition heals, i.e. the zombie's writes
            # arrive LATE rather than never
            logging.info("faultinject: holding %s for %.1fs (partition)",
                         cmd, hold)
            time.sleep(hold)
        try:
            upstream.sendall(rpc)
        except OSError:
            self._hard_reset(client)
            return False
        if reset_after:
            # the AMBIGUOUS drop: request forwarded (the graceful upstream
            # close in _serve's finally lets the service read and apply
            # it), but the client connection dies reply-less
            logging.info("faultinject: reset (after) on %s", cmd)
            self._hard_reset(client)
            return False
        return True


# ====================================================== preemption delivery
#
# The PREEMPT fault plane: a planned eviction is a real SIGTERM followed,
# one grace window later, by a real SIGKILL — exactly what a spot VM or a
# TPU maintenance event delivers. Available as a wire-plan op
# (``{"op": "preempt", "match": "STEP", "nth": 20, "deadline_s": 5}`` on
# a FaultyProxy supervising a training subprocess) and directly as
# :func:`deliver_preemption` for chaos harnesses that schedule the
# eviction on wall time instead of RPC counts. The target's preemption
# plane (``runtime/preemption.py``) must rescue-checkpoint and hand off
# INSIDE the window; the SIGKILL is unconditional — the platform never
# waits for a well-behaved guest.


def deliver_preemption(pid: int, deadline_s: Optional[float] = None,
                       reason: str = "faultinject") -> threading.Thread:
    """SIGTERM ``pid`` now; SIGKILL it ``deadline_s`` seconds later if it
    is still alive (a process that departed gracefully — exit 0 inside
    the window — is never touched). Returns the (daemon) killer thread
    so harnesses can join it."""
    if deadline_s is None:
        deadline_s = const.ENV.ADT_PREEMPT_DEADLINE_S.val
    deadline_s = float(deadline_s)
    logging.warning("faultinject: PREEMPT pid %d — SIGTERM now, SIGKILL "
                    "in %.1fs (%s)", pid, deadline_s, reason)
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        logging.warning("faultinject: preempt target pid %d already gone",
                        pid)

    def kill_at_deadline():
        time.sleep(deadline_s)
        try:
            os.kill(pid, signal.SIGKILL)
            logging.warning("faultinject: preempt deadline hit — SIGKILLed "
                            "pid %d", pid)
        except ProcessLookupError:
            pass  # departed inside the window: the graceful path won

    t = threading.Thread(target=kill_at_deadline,
                         name="adt-preempt-killer", daemon=True)
    t.start()
    return t


# ===================================================== checkpoint lifecycle
#
# The wire proxy above faults the COORDINATION plane; this layer faults the
# CHECKPOINT plane: deterministic SIGKILLs at save-lifecycle phase points
# and post-commit file damage (truncation / bit flips), driven by the same
# declarative-plan idiom through ``ADT_CKPT_FAULT_PLAN``::
#
#     {
#       "kills":  [{"phase": "meta", "nth": 3}],
#       "damage": [{"op": "bitflip",  "phase": "committed",
#                   "file": "shard-p0.npz", "nth": 1, "offset": -4096},
#                  {"op": "truncate", "phase": "committed",
#                   "file": "params.npz",  "nth": 1, "bytes": 64}]
#     }
#
# Phase points the savers call ``checkpoint_fault(phase, ...)`` at:
#
# - ``collect``   — state gathered to host, nothing on disk yet
# - ``write``     — data fully written to ``.tmp`` files, none replaced
# - ``index``     — shard npz replaced into place, index not yet written
#   (sharded saver only)
# - ``meta``      — all data + index files final, meta (the commit point)
#   not yet written
# - ``committed`` — meta replaced: the checkpoint is durable
#
# A ``kill`` rule delivers a real ``SIGKILL`` to this process at its
# phase's nth firing — no atexit, no flushing, the crash the atomic-write
# protocol must survive. A ``damage`` rule mutates the bytes of a matching
# file at its phase — ``committed`` models post-commit bit rot a restore
# must detect and fall back from; earlier phases model a filesystem that
# tore a write the checksums must catch.
#
# Matching is deterministic exactly like the wire rules: per-rule nth
# counters under one lock, no randomness unless ``prob`` is given — a
# probabilistic rule rolls against the plan-level rng (``"seed"`` key,
# default 0) once armed, and stays armed on a failed roll.


def truncate_file(path: str, keep_bytes: int):
    """Truncate ``path`` to its first ``keep_bytes`` bytes — the classic
    torn write (also usable directly from tests)."""
    with open(path, "r+b") as f:
        f.truncate(max(0, int(keep_bytes)))


def flip_bit(path: str, offset: int = -1):
    """XOR one bit at byte ``offset`` (negative = from the end; default
    flips a bit near the middle of the file) — silent single-bit rot."""
    size = os.path.getsize(path)
    if size == 0:
        return
    if offset == -1:
        offset = size // 2
    if offset < 0:
        offset = max(0, size + offset)
    offset = min(offset, size - 1)
    with open(path, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ 0x01]))


def _kill_self():  # separated so tests can intercept the kill
    os.kill(os.getpid(), signal.SIGKILL)


class CkptFaultRule:
    """One checkpoint-lifecycle fault (kill or damage)."""

    def __init__(self, spec: dict, op: Optional[str] = None):
        self.op = op or spec.get("op")
        if self.op not in ("kill", "truncate", "bitflip"):
            raise ValueError("unknown checkpoint fault op %r" % self.op)
        self.phase = spec.get("phase", "committed" if self.op != "kill"
                              else "write")
        self.file = spec.get("file", "")
        self.nth = int(spec.get("nth", 1))
        self.repeat = bool(spec.get("repeat", False))
        self.bytes = int(spec.get("bytes", 0))
        self.offset = int(spec.get("offset", -1))
        self.prob = float(spec.get("prob", 1.0))
        self._matched = 0
        self._spent = False

    def should_fire(self, phase: str, rng: random.Random) -> bool:
        if self._spent or phase != self.phase:
            return False
        self._matched += 1
        if self._matched < self.nth:
            return False
        if self.prob < 1.0 and rng.random() >= self.prob:
            # stayed armed at the threshold: the next matching phase
            # point re-rolls (seeded rng — deterministic per plan)
            self._matched -= 1
            return False
        if self.repeat:
            self._matched = 0
        else:
            self._spent = True
        return True


class CheckpointFaultPlan:
    """Parsed ``ADT_CKPT_FAULT_PLAN`` — see the section comment above."""

    def __init__(self, spec: Optional[dict] = None):
        spec = spec or {}
        self.rules: List[CkptFaultRule] = (
            [CkptFaultRule(r, op="kill") for r in spec.get("kills", ())] +
            [CkptFaultRule(r) for r in spec.get("damage", ())])
        self.rng = random.Random(int(spec.get("seed", 0)))
        self.lock = threading.Lock()
        self.injected: List[str] = []

    @classmethod
    def from_env(cls) -> "CheckpointFaultPlan":
        raw = const.ENV.ADT_CKPT_FAULT_PLAN.val
        if not raw:
            return cls()
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        elif os.path.exists(raw):
            with open(raw) as f:
                raw = f.read()
        return cls(json.loads(raw))

    def _targets(self, rule: CkptFaultRule, path: Optional[str]) -> List[str]:
        """Files a damage rule applies to at this phase point. ``path`` is
        either one concrete file or a checkpoint base (``.../ckpt-N``)
        whose sibling files are matched by the rule's ``file`` substring."""
        if path is None:
            return []
        if os.path.isfile(path):
            return [path] if rule.file in os.path.basename(path) else []
        directory, base = os.path.dirname(path), os.path.basename(path)
        try:
            names = os.listdir(directory)
        except OSError:
            return []
        return [os.path.join(directory, f) for f in sorted(names)
                if f.startswith(base + ".") and rule.file and rule.file in f]

    def fire(self, phase: str, path: Optional[str] = None,
             step: Optional[int] = None):
        with self.lock:
            fired = [r for r in self.rules if r.should_fire(phase, self.rng)]
        for rule in fired:
            if rule.op == "kill":
                logging.warning(
                    "faultinject: SIGKILL at checkpoint phase %r (step %s)",
                    phase, step)
                for h in logging.get_logger().handlers:
                    h.flush()  # SIGKILL gives no atexit: flush by hand
                self.injected.append("kill:%s" % phase)
                _kill_self()
                continue  # only reached when _kill_self is intercepted
            for target in self._targets(rule, path):
                logging.warning("faultinject: %s on %s at phase %r",
                                rule.op, target, phase)
                if rule.op == "truncate":
                    truncate_file(target, rule.bytes)
                else:
                    flip_bit(target, rule.offset)
                self.injected.append("%s:%s" % (rule.op,
                                                os.path.basename(target)))


_ckpt_plan_lock = threading.Lock()
_ckpt_plan: Optional[CheckpointFaultPlan] = None
_ckpt_plan_raw: Optional[str] = None


def checkpoint_fault(phase: str, path: Optional[str] = None,
                     step: Optional[int] = None):
    """Phase hook the checkpoint savers call at every lifecycle point.
    A no-op (one env read) unless ``ADT_CKPT_FAULT_PLAN`` is set; the
    plan is parsed once and re-parsed only when the env value changes
    (tests swap plans in-process)."""
    global _ckpt_plan, _ckpt_plan_raw
    raw = const.ENV.ADT_CKPT_FAULT_PLAN.val
    if not raw:
        return
    with _ckpt_plan_lock:
        if raw != _ckpt_plan_raw:
            _ckpt_plan = CheckpointFaultPlan.from_env()
            _ckpt_plan_raw = raw
        plan = _ckpt_plan
    plan.fire(phase, path=path, step=step)


# ======================================================== gradient faults
#
# The wire proxy faults the COORDINATION plane and the checkpoint plan
# faults the DISK; this layer faults the COMPUTATION — the one class the
# chaos harness could not previously represent: a poisoned gradient
# flowing into an update. Same declarative grammar, through
# ``ADT_GRAD_FAULT_PLAN``::
#
#     {
#       "seed": 0,
#       "faults": [
#         {"var": "w",   "mode": "nan",     "step": 3},
#         {"var": "w",   "mode": "bitflip", "step": 5, "until": 7,
#          "bit": 30, "index": 0},
#         {"var": "emb", "mode": "scale",   "step": 4, "factor": 1e6},
#         {"var": "b",   "mode": "inf",     "step": 2, "every": 4,
#          "until": 100}
#       ]
#     }
#
# Unlike the wire/checkpoint plans (host-side hooks, re-read per call),
# gradient faults are COMPILED INTO the lowering: ``GraphTransformer``
# reads the plan at transform time and traces each rule as a
# ``jnp.where(step == n, poison, grad)`` branch keyed on the TrainState's
# own step counter — so injection works identically in the per-step and
# fused ``lax.scan`` paths, costs zero extra dispatches, and is exactly
# reproducible (the step counter, not wall time, arms it). Consequence:
# the plan must be set BEFORE the program is built, and a rollback that
# replays the faulty step window re-encounters the same faults — which is
# precisely what the sentinel's escalation ladder is tested against.
#
# Rule fields: ``var`` (exact variable name, required), ``mode`` in
# ``nan | inf | bitflip | scale``, ``step`` (0-based TrainState step the
# fault arms at), ``until`` (inclusive last step; default = ``step``, so
# a bare rule is a one-step transient), ``every`` (within [step, until]
# fire only when (step - rule.step) % every == 0), ``factor`` (scale
# mode, default 1e6), ``bit``/``index`` (bitflip mode: XOR bit ``bit`` of
# the flat element at ``index``; bit 30 flips a float32 exponent MSB —
# the classic silent-data-corruption blowup).


class GradFaultRule:
    """One declarative gradient fault (see the section comment above).

    Unknown fields are REJECTED, not ignored: the wire/ckpt grammars'
    ``nth``/``repeat``/``prob`` knobs do not exist here (injection is
    traced, keyed on the step counter, with no runtime roll), and a
    silently-dropped field would make the chaos run test something other
    than what the plan declares."""

    _MODES = ("nan", "inf", "bitflip", "scale")
    _FIELDS = frozenset(("var", "mode", "step", "until", "every",
                         "factor", "bit", "index"))

    def __init__(self, spec: dict):
        unknown = sorted(set(spec) - self._FIELDS)
        if unknown:
            raise ValueError(
                "unknown gradient fault field(s) %s — the grad plan is "
                "step-keyed (fields: %s); nth/repeat/prob belong to the "
                "wire/checkpoint plans (docs/failure_model.md)"
                % (unknown, ", ".join(sorted(self._FIELDS))))
        self.var = spec["var"]
        self.mode = spec.get("mode", "nan")
        if self.mode not in self._MODES:
            raise ValueError("unknown gradient fault mode %r (one of %s)"
                             % (self.mode, ", ".join(self._MODES)))
        self.step = int(spec.get("step", 0))
        self.until = int(spec.get("until", self.step))
        if self.until < self.step:
            raise ValueError("gradient fault until=%d precedes step=%d"
                             % (self.until, self.step))
        self.every = max(1, int(spec.get("every", 1)))
        self.factor = float(spec.get("factor", 1e6))
        self.bit = int(spec.get("bit", 30))
        self.index = int(spec.get("index", 0))

    def describe(self) -> str:
        window = ("step %d" % self.step if self.until == self.step
                  else "steps %d..%d/%d" % (self.step, self.until,
                                            self.every))
        return "%s(%s @ %s)" % (self.mode, self.var, window)


class GradFaultPlan:
    """Parsed ``ADT_GRAD_FAULT_PLAN`` — consumed by ``GraphTransformer``
    at transform time (the traced-injection contract above). A top-level
    ``seed`` is tolerated for grammar-family symmetry but meaningless:
    grad injection is fully deterministic (step-keyed, no rng)."""

    def __init__(self, spec: Optional[dict] = None):
        spec = spec or {}
        self.rules: List[GradFaultRule] = [GradFaultRule(r)
                                           for r in spec.get("faults", ())]

    @classmethod
    def from_env(cls) -> "GradFaultPlan":
        raw = const.ENV.ADT_GRAD_FAULT_PLAN.val
        if not raw:
            return cls()
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        elif os.path.exists(raw):
            with open(raw) as f:
                raw = f.read()
        return cls(json.loads(raw))

    def describe(self) -> str:
        return ", ".join(r.describe() for r in self.rules)


def _uint_like(dtype):
    """The same-width unsigned dtype for a bitcast (bitflip mode)."""
    import numpy as _np
    return {2: _np.uint16, 4: _np.uint32, 8: _np.uint64}[
        _np.dtype(dtype).itemsize]


def apply_grad_faults(plan: GradFaultPlan, step, grads: dict) -> dict:
    """TRACED application of a grad-fault plan: ``step`` is the (possibly
    abstract) TrainState step counter, ``grads`` a name->array dict; every
    matching rule contributes a data-dependent select, so the compiled
    program injects at exactly the planned steps with no recompile and no
    host round-trip. Rules naming absent variables are skipped (the
    transformer warns about them once at build time)."""
    import jax
    import jax.numpy as jnp
    out = dict(grads)
    for rule in plan.rules:
        g = out.get(rule.var)
        if g is None or not jnp.issubdtype(jnp.asarray(g).dtype,
                                           jnp.inexact):
            continue
        g = jnp.asarray(g)
        hit = (step >= rule.step) & (step <= rule.until)
        if rule.every > 1:
            hit = hit & ((step - rule.step) % rule.every == 0)
        if rule.mode == "nan":
            out[rule.var] = g + jnp.where(hit, jnp.nan, 0.0).astype(g.dtype)
        elif rule.mode == "inf":
            out[rule.var] = g + jnp.where(hit, jnp.inf, 0.0).astype(g.dtype)
        elif rule.mode == "scale":
            out[rule.var] = g * jnp.where(hit, rule.factor, 1.0).astype(
                g.dtype)
        else:  # bitflip: XOR one bit of one element — silent corruption
            flat = g.reshape(-1)
            size = int(flat.shape[0])
            idx = rule.index % size
            udt = _uint_like(g.dtype)
            bit = rule.bit % (8 * jnp.dtype(udt).itemsize)
            bits = jax.lax.bitcast_convert_type(flat[idx], udt)
            flipped = jax.lax.bitcast_convert_type(
                bits ^ udt(1 << bit), g.dtype)
            flat = flat.at[idx].set(jnp.where(hit, flipped, flat[idx]))
            out[rule.var] = flat.reshape(g.shape)
    return out
