"""Cluster — multi-host process management.

Analog of reference ``autodist/cluster.py:51-374`` (``Cluster``/``SSHCluster``).
The reference builds a TF ClusterSpec with deterministic sorted port
assignment, starts a ``tf.distribute.Server`` per node (local Popen for the
chief, SSH for remotes), and SIGTERMs process groups at exit. On TPU there
is no separate server process: each worker *client* process joins the JAX
distributed runtime (``jax.distributed.initialize``) and the TPU runtime's
coordination service (hosted by process 0) replaces the gRPC server mesh.
What remains of the Cluster is:

- the deterministic process layout: sorted node addresses -> process ids
  (the determinism the reference gets from sorted ip:port ordering,
  ``cluster.py:70-82``),
- the deployment plane: SSH/SCP helpers to ship files and launch remote
  commands (reference ``cluster.py:316-374``), honoring ``ADT_DEBUG_REMOTE``
  for dry-runs exactly like ``AUTODIST_DEBUG_REMOTE``
  (reference ``cluster.py:340-341``),
- teardown: terminating launched remote processes at exit
  (reference ``cluster.py:176,212-216``).
"""
import atexit
import os
import shlex
import signal
import subprocess
import time
from typing import Dict, List, Optional

from autodist_tpu import const
from autodist_tpu.resource_spec import ResourceSpec, SSHConfig
from autodist_tpu.utils import logging


class Cluster:
    """Process layout + lifecycle for one training job."""

    def __init__(self, resource_spec: ResourceSpec,
                 coordinator_port=None, coordsvc_port=None):
        self._spec = resource_spec
        # explicit arg > ADT_COORDINATOR_ADDR's port > default — two
        # colocated jobs (or parallel test runs) must not both bind the
        # default port; the env is already honored on the worker side
        if coordinator_port is None:
            addr = const.ENV.ADT_COORDINATOR_ADDR.val
            if addr and ":" in addr:
                coordinator_port = int(addr.rsplit(":", 1)[1])
            else:
                coordinator_port = const.DEFAULT_COORDINATOR_PORT
        self._port = coordinator_port
        # single source of truth for the native coordination-service port
        # (server bring-up here, watchdog client in the Coordinator);
        # default resolved at construction so ADT_COORDSVC_PORT set after
        # import still applies
        self.coordsvc_port = (coordsvc_port if coordsvc_port is not None
                              else const.ENV.ADT_COORDSVC_PORT.val)
        # deterministic: chief first, then remaining addresses sorted
        others = [a for a in resource_spec.node_addresses if a != resource_spec.chief]
        self._process_addresses: List[str] = [resource_spec.chief] + others
        self._procs: List[subprocess.Popen] = []
        self._started = False

    # ------------------------------------------------------------- layout

    @property
    def num_processes(self) -> int:
        return len(self._process_addresses)

    @property
    def coordinator_address(self) -> str:
        return "%s:%d" % (self._spec.chief, self._port)

    def process_id(self, address: str) -> int:
        return self._process_addresses.index(address)

    @property
    def process_addresses(self) -> List[str]:
        return list(self._process_addresses)

    def is_chief(self, address: Optional[str] = None) -> bool:
        if address is None:
            return const.is_chief()
        return address == self._spec.chief

    def reconfigure(self, roster: List[str], epoch: int):
        """Adopt an elastic epoch's roster as THIS job's process set (the
        chief-side half of ``elastic.rejoin_process_set``): update the
        deterministic layout, then tear down and re-join jax.distributed
        as the smaller (shrink) or larger (grow-on-join) world. Workers
        never hold a Cluster — they call ``rejoin_process_set`` directly
        from the Runner's reconfigure path with the same layout rule, so
        every member computes identical process ids."""
        from autodist_tpu.runtime import elastic
        layout = elastic.roster_layout(roster, self._spec.chief)
        self._process_addresses = layout
        self.epoch = epoch
        os.environ[const.ENV.ADT_NUM_PROCESSES.name_str] = str(len(layout))
        elastic.rejoin_process_set(layout, epoch, chief=self._spec.chief)

    def worker_env(self, address: str) -> Dict[str, str]:
        """Env vars that turn a launched script into worker ``address``."""
        return {
            const.ENV.ADT_WORKER.name_str: address,
            const.ENV.ADT_COORDINATOR_ADDR.name_str: self.coordinator_address,
            const.ENV.ADT_NUM_PROCESSES.name_str: str(self.num_processes),
            const.ENV.ADT_PROCESS_ID.name_str: str(self.process_id(address)),
        }

    # ------------------------------------------------------------ lifecycle

    def start(self):
        """Initialize the distributed runtime on the chief: bring up the
        native coordination service (barriers/staleness/heartbeats — the
        reference's per-node TF server role) and join jax.distributed.
        Workers join via ``server_starter.maybe_init_distributed`` when their
        (relaunched) script constructs AutoDist."""
        if self._started:
            return
        if const.is_chief() and not const.ENV.ADT_DEBUG_REMOTE.val:
            from autodist_tpu.runtime.coordination import CoordinationServer
            try:
                self._coordsvc = CoordinationServer(self.coordsvc_port)
                self._coordsvc.start()
                atexit.register(self._coordsvc.stop)

            except (RuntimeError, TimeoutError, OSError,
                    subprocess.CalledProcessError) as e:
                logging.warning("coordination service unavailable: %s", e)
                self._coordsvc = None
        from autodist_tpu.runtime import server_starter
        if (const.ENV.ADT_ELASTIC.val > 0
                and not const.ENV.ADT_ELASTIC_SYNC.val):
            # elastic async-PS jobs keep the process set OPEN (workers may
            # die and be relaunched); jax.distributed would pin it shut.
            # Sync-elastic (ADT_ELASTIC_SYNC) joins: lockstep collectives
            # need the global mesh, and recovery is a whole-job re-exec
            # with a fresh process set rather than a rejoin.
            logging.info("elastic mode: chief not joining jax.distributed")
            server_starter.mark_elastic_started()
        else:
            server_starter.init_distributed(
                coordinator_address=self.coordinator_address,
                num_processes=self.num_processes,
                process_id=self.process_id(
                    const.ENV.ADT_WORKER.val or self._spec.chief))
        atexit.register(self.terminate)
        self._started = True

    def stop_coordination_service(self):
        """Stop the service child this cluster started (sync-elastic
        re-exec: os.execv skips atexit, and an orphaned server would hold
        the port and carry the crashed incarnation's state into the
        resumed job)."""
        svc = getattr(self, "_coordsvc", None)
        if svc is not None:
            try:
                svc.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
            self._coordsvc = None

    def terminate(self, grace_s: float = 10.0):
        """Terminate launched worker process groups (reference
        ``cluster.py:176``), giving a clean-finishing job a grace window
        first: the last collective syncs all processes, but trailing
        local work (writing outputs) is not lockstep — killing on sight
        truncates a worker that is milliseconds from a clean exit."""
        deadline = time.monotonic() + grace_s
        for p in self._procs:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                pass
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError, OSError):
                    p.terminate()
        self._procs.clear()

    # ------------------------------------------------------ remote helpers

    def _is_local(self, address: str) -> bool:
        """Loopback addresses (and nodes whose ssh_config says
        ``transport: local``) execute through local bash/cp instead of
        ssh/scp — the chief->worker launch path runs for real on one
        machine, no sshd required (the reference's 2-node CI stage,
        ``Jenkinsfile`` 'test-distributed', needed real machines)."""
        conf = self._spec.ssh_config_map.for_host(address)
        if conf is not None:
            # an explicit ssh_config wins: a loopback address with ssh
            # config is the port-forward-to-remote-host pattern (ssh -p
            # 2222 127.0.0.1 reaches a DIFFERENT machine) and must keep
            # going through ssh unless the config opts into local
            return conf.transport == "local"
        return address in ("localhost", "127.0.0.1", "::1")

    def _ssh_base(self, address: str) -> List[str]:
        conf: Optional[SSHConfig] = self._spec.ssh_config_map.for_host(address)
        cmd = ["ssh", "-oStrictHostKeyChecking=no", "-oBatchMode=yes",
               "-oConnectTimeout=10"]
        if conf:
            if conf.key_file:
                cmd += ["-i", conf.key_file]
            if conf.port != 22:
                cmd += ["-p", str(conf.port)]
            target = ("%s@%s" % (conf.username, address)) if conf.username else address
        else:
            target = address
        return cmd + [target]

    def remote_exec(self, command: str, address: str,
                    env: Optional[Dict[str, str]] = None,
                    wait: bool = False) -> Optional[subprocess.Popen]:
        """Launch a shell command on a remote node. ``wait=False`` returns the
        local ssh Popen (tracked for exit-time SIGTERM); ``wait=True`` blocks
        until completion and tracks nothing. Dry-run under ADT_DEBUG_REMOTE."""
        conf = self._spec.ssh_config_map.for_host(address)
        env_prefix = ""
        merged = dict(conf.env) if conf else {}
        merged.update(env or {})
        if merged:
            env_prefix = " ".join("%s=%s" % (k, shlex.quote(str(v)))
                                  for k, v in sorted(merged.items())) + " "
        venv = ("source %s/bin/activate && " % conf.python_venv
                if conf and conf.python_venv else "")
        line = venv + env_prefix + command
        if self._is_local(address):
            full = ["bash", "-c", line]
            logging.info("local_exec[%s]: %s", address, line)
        else:
            full = self._ssh_base(address) + ["bash -c %s" % shlex.quote(line)]
            logging.info("remote_exec[%s]: %s", address, " ".join(full))
        if const.ENV.ADT_DEBUG_REMOTE.val:
            return None
        if wait:
            subprocess.run(full, check=False)
            return None
        proc = subprocess.Popen(full, preexec_fn=os.setsid)
        self._procs.append(proc)
        return proc

    def remote_copy(self, local_path: str, remote_dir: str, address: str) -> bool:
        """SCP a file to a remote node (reference ``remote_copy``); plain
        cp for local-transport nodes (self-copy skipped)."""
        if self._is_local(address):
            logging.info("local_copy[%s]: %s -> %s", address, local_path,
                         remote_dir)
            if const.ENV.ADT_DEBUG_REMOTE.val:
                return True
            import shutil
            os.makedirs(remote_dir, exist_ok=True)
            dest = os.path.join(remote_dir, os.path.basename(local_path))
            if os.path.abspath(local_path) != os.path.abspath(dest):
                shutil.copy2(local_path, dest)
            return True
        conf = self._spec.ssh_config_map.for_host(address)
        cmd = ["scp", "-oStrictHostKeyChecking=no", "-oBatchMode=yes"]
        if conf:
            if conf.key_file:
                cmd += ["-i", conf.key_file]
            if conf.port != 22:
                cmd += ["-P", str(conf.port)]
            target = ("%s@%s" % (conf.username, address)) if conf.username else address
        else:
            target = address
        self.remote_exec("mkdir -p %s" % shlex.quote(remote_dir), address,
                         wait=True)
        cmd += [local_path, "%s:%s/" % (target, remote_dir)]
        logging.info("remote_copy[%s]: %s", address, " ".join(cmd))
        if const.ENV.ADT_DEBUG_REMOTE.val:
            return True
        return subprocess.run(cmd, check=False).returncode == 0


class SSHCluster(Cluster):
    """Named alias mirroring the reference's concrete class
    (``cluster.py:271-374``); all SSH mechanics live in Cluster."""
