"""Framework logger.

Analog of reference ``autodist/utils/logging.py:80-107``: a dedicated
``autodist_tpu`` logger with PID+file+line formatting, writing to both stderr
and a per-run file under ``/tmp/autodist_tpu/logs/<timestamp>.log``; level
taken from the ``ADT_MIN_LOG_LEVEL`` env var.
"""
import logging as _logging
import os
import sys
import time
import threading

from autodist_tpu import const

_logger = None
_logger_lock = threading.Lock()

_FMT = "%(asctime)s %(levelname).1s %(process)d %(filename)s:%(lineno)d] %(message)s"


def get_logger() -> _logging.Logger:
    global _logger
    if _logger is not None:
        return _logger
    with _logger_lock:
        if _logger is not None:
            return _logger
        logger = _logging.getLogger("autodist_tpu")
        logger.propagate = False
        level = const.ENV.ADT_MIN_LOG_LEVEL.val.upper()
        logger.setLevel(getattr(_logging, level, _logging.INFO))
        fmt = _logging.Formatter(_FMT)
        sh = _logging.StreamHandler(sys.stderr)
        sh.setFormatter(fmt)
        logger.addHandler(sh)
        try:
            os.makedirs(const.DEFAULT_LOG_DIR, exist_ok=True)
            path = os.path.join(const.DEFAULT_LOG_DIR, "%d-%d.log" % (int(time.time()), os.getpid()))
            fh = _logging.FileHandler(path)
            fh.setFormatter(fmt)
            logger.addHandler(fh)
        except OSError:
            pass
        _logger = logger
        return logger


def debug(msg, *args, **kw):
    get_logger().debug(msg, *args, stacklevel=2, **kw)


def info(msg, *args, **kw):
    get_logger().info(msg, *args, stacklevel=2, **kw)


def warning(msg, *args, **kw):
    get_logger().warning(msg, *args, stacklevel=2, **kw)


def error(msg, *args, **kw):
    get_logger().error(msg, *args, stacklevel=2, **kw)


def set_verbosity(level: str):
    get_logger().setLevel(getattr(_logging, level.upper(), _logging.INFO))
