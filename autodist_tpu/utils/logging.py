"""Framework logger.

Analog of reference ``autodist/utils/logging.py:80-107``: a dedicated
``autodist_tpu`` logger with PID+file+line formatting, writing to both stderr
and a per-run file under ``/tmp/autodist_tpu/logs/<timestamp>.log``; level
taken from the ``ADT_MIN_LOG_LEVEL`` env var.

``ADT_LOG_FORMAT=json`` switches both handlers to structured one-object-
per-line JSON carrying the ACTIVE TELEMETRY SPAN ID (``telemetry/spans.py``)
so log lines correlate with trace timelines — a resilience retry warning
lands inside the ``coord.backoff`` span that slept it, a degraded-pull
warning inside its ``ps.pull``. ``set_format()`` switches a live logger.
"""
import json as _json
import logging as _logging
import os
import sys
import time
import threading

from autodist_tpu import const

_logger = None
_logger_lock = threading.Lock()

_FMT = "%(asctime)s %(levelname).1s %(process)d %(filename)s:%(lineno)d] %(message)s"


class _JsonFormatter(_logging.Formatter):
    """One JSON object per line: stable keys, ISO-ish timestamp, and the
    innermost live telemetry span id (0 = no span active) so a log
    pipeline can join lines onto the exported trace."""

    def format(self, record: _logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "pid": record.process,
            "thread": record.threadName,
            "src": "%s:%d" % (record.filename, record.lineno),
            "msg": record.getMessage(),
        }
        try:  # lazy: logging must work even mid-import of telemetry
            from autodist_tpu.telemetry import spans as _tspans
            span_id = _tspans.current_span_id()
            if span_id:
                out["span_id"] = span_id
        except Exception:  # noqa: BLE001 — correlation is best-effort
            pass
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return _json.dumps(out)


def make_formatter(fmt: str = None) -> _logging.Formatter:
    """The formatter for a format mode ("text" | "json"; default: the
    ``ADT_LOG_FORMAT`` env var)."""
    mode = (fmt or const.ENV.ADT_LOG_FORMAT.val or "text").lower()
    if mode == "json":
        return _JsonFormatter()
    return _logging.Formatter(_FMT)


def get_logger() -> _logging.Logger:
    global _logger
    if _logger is not None:
        return _logger
    with _logger_lock:
        if _logger is not None:
            return _logger
        logger = _logging.getLogger("autodist_tpu")
        logger.propagate = False
        level = const.ENV.ADT_MIN_LOG_LEVEL.val.upper()
        logger.setLevel(getattr(_logging, level, _logging.INFO))
        fmt = make_formatter()
        sh = _logging.StreamHandler(sys.stderr)
        sh.setFormatter(fmt)
        logger.addHandler(sh)
        try:
            os.makedirs(const.DEFAULT_LOG_DIR, exist_ok=True)
            path = os.path.join(const.DEFAULT_LOG_DIR, "%d-%d.log" % (int(time.time()), os.getpid()))
            fh = _logging.FileHandler(path)
            fh.setFormatter(fmt)
            logger.addHandler(fh)
        except OSError:
            pass
        _logger = logger
        return logger


def set_format(fmt: str):
    """Switch a live logger's handlers between "text" and "json" lines
    (tests / long-running jobs flipping to structured output)."""
    formatter = make_formatter(fmt)
    for handler in get_logger().handlers:
        handler.setFormatter(formatter)


def debug(msg, *args, **kw):
    get_logger().debug(msg, *args, stacklevel=2, **kw)


def info(msg, *args, **kw):
    get_logger().info(msg, *args, stacklevel=2, **kw)


def warning(msg, *args, **kw):
    get_logger().warning(msg, *args, stacklevel=2, **kw)


def error(msg, *args, **kw):
    get_logger().error(msg, *args, stacklevel=2, **kw)


def set_verbosity(level: str):
    get_logger().setLevel(getattr(_logging, level.upper(), _logging.INFO))
