"""Bind mesh axis names for out-of-mesh tracing.

Several analyses (sparse-var detection in ``model_item``, sparse-wire
discovery and tied-table safety in ``kernel/graph_transformer``) trace the
user's loss function OUTSIDE the training step's ``shard_map``. A loss
that uses mesh collectives — ``psum("model")`` in Megatron layers,
``axis_index("seq")`` in ring attention — cannot trace bare: the axis
names are unbound. This context manager binds them (jax's axis
environment, the same mechanism ``pmap``/``shard_map`` use), so shapes
and jaxprs come out exactly as inside the step, without wrapping the
function in a ``shard_map`` that the jaxpr analyses would then have to
see through.
"""
import contextlib
from typing import Dict, Optional

from autodist_tpu import const

FRAMEWORK_AXES = (const.DATA_AXIS, const.MODEL_AXIS, const.PIPELINE_AXIS,
                  const.SEQUENCE_AXIS, const.EXPERT_AXIS)


@contextlib.contextmanager
def bound_axes(sizes: Optional[Dict[str, int]] = None):
    """Bind every framework axis name (default size 1; pass the real mesh
    sizes when shape math depends on them). Falls back to a no-op if the
    private jax API moved — callers' own try/except then reports the
    unbound-axis failure exactly as before."""
    try:
        from jax._src.core import extend_axis_env_nd
    except ImportError:  # pragma: no cover - jax moved the API
        yield
        return
    sizes = sizes or {}
    frame = [(name, int(sizes.get(name, 1))) for name in FRAMEWORK_AXES]
    with extend_axis_env_nd(frame):
        yield
