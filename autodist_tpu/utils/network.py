"""Network/address utilities.

Analog of reference ``autodist/utils/network.py:21-75`` (loopback/local
address detection via netifaces) — used to decide whether a resource-spec
node address refers to this machine (chief-vs-remote launch decisions).
Implemented with the stdlib only.
"""
import socket
from typing import Set


def _local_addresses() -> Set[str]:
    addrs = {"127.0.0.1", "localhost", "::1"}
    hostname = socket.gethostname()
    addrs.add(hostname)
    try:
        addrs.update(info[4][0] for info in socket.getaddrinfo(hostname, None))
    except socket.gaierror:
        pass
    try:
        # UDP connect trick: learn the outbound-interface address
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        addrs.add(s.getsockname()[0])
        s.close()
    except OSError:
        pass
    return addrs


def _host_of(address: str) -> str:
    """Strip device/port suffixes: 'h:TPU:0' -> 'h', '[::1]:80' -> '::1',
    bare IPv6 like '::1' passes through unchanged."""
    if address.startswith("["):  # bracketed IPv6
        return address[1:].split("]")[0]
    try:
        import ipaddress
        ipaddress.IPv6Address(address)
        return address
    except (ValueError, ImportError):
        pass
    return address.split(":")[0]


def is_loopback_address(address: str) -> bool:
    return _host_of(address) in ("127.0.0.1", "localhost", "::1")


def is_local_address(address: str) -> bool:
    return _host_of(address) in _local_addresses()
