"""Compile-time program snapshots.

Analog of reference ``autodist/utils/visualization_util.py:24-36``, which
writes the graph to TensorBoard event files at each transformation stage
(``0-original`` … ``3-transformed``, ``kernel/graph_transformer.py:62-90``).
The JAX equivalents of "the graph" are the jaxpr and the lowered StableHLO:
``log_program`` dumps them as text under
``/tmp/autodist_tpu/snapshots/<run>/<stage>.txt``, gated by the
``ADT_SNAPSHOT`` env var or an explicit call.
"""
import os
import time

from autodist_tpu import const
from autodist_tpu.utils import logging

_RUN_DIR = None


def _run_dir() -> str:
    global _RUN_DIR
    if _RUN_DIR is None:
        _RUN_DIR = os.path.join(const.DEFAULT_SNAPSHOT_DIR,
                                time.strftime("%Y%m%d-%H%M%S"))
        os.makedirs(_RUN_DIR, exist_ok=True)
    return _RUN_DIR


def enabled() -> bool:
    return os.environ.get("ADT_SNAPSHOT", "") not in ("", "0")


def log_program(stage: str, text: str, force: bool = False):
    """Write one stage's program text (jaxpr or HLO)."""
    if not (force or enabled()):
        return
    path = os.path.join(_run_dir(), "%s.txt" % stage)
    with open(path, "w") as f:
        f.write(text)
    logging.debug("snapshot %s -> %s", stage, path)


def log_jaxpr(stage: str, fn, *example_args, force: bool = False):
    if not (force or enabled()):
        return
    import jax
    try:
        log_program(stage, str(jax.make_jaxpr(fn)(*example_args)), force=force)
    except Exception as e:  # noqa: BLE001 — diagnostics must not break builds
        logging.warning("snapshot %s failed: %s", stage, e)
