// Native data loader — threaded host-side input pipeline.
//
// TPU-native replacement for the input-pipeline muscle the reference
// borrows from TensorFlow's C++ runtime (tf.data iterators / queue runners;
// SURVEY §2.0 notes all native functionality in the reference is stock TF).
// Training on TPU is fed from the host: record files must be read,
// shuffled, and assembled into fixed-shape batches fast enough to hide
// behind device compute. Python threads cannot do this off the GIL; these
// worker threads can.
//
// Scope: fixed-size binary records (the "ADT1" format written by
// autodist_tpu.data.RecordFileWriter — field layout lives in a Python-side
// sidecar; C++ sees opaque record_bytes). Workers gather shuffled records
// into a ring of reusable batch buffers; delivery is in batch order, so a
// given seed yields one deterministic stream regardless of thread count.
//
// Exposed as a C ABI (built into libadt_dataloader.so) consumed via ctypes
// from autodist_tpu/data/record_dataset.py.
//
// File format ADT1:
//   magic  "ADT1"            4 bytes
//   n_records                uint64 LE
//   record_bytes             uint64 LE
//   payload                  n_records * record_bytes
//
// Semantics: infinite stream over the file; each epoch is a fresh
// permutation (xorshift64* seeded from (seed, epoch)); trailing records
// that don't fill a batch are dropped (TPU static shapes).
//
// Sharding (multi-host input): adl_open_sharded(shard_index, shard_count)
// restricts the stream to the strided record subset
// {i : i % shard_count == shard_index} — every process reads a DISJOINT
// 1/shard_count slice of the file instead of materializing the global
// batch everywhere. Same seed + different shard_index streams are
// disjoint by construction.

#include <fcntl.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <numeric>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::vector<uint8_t> data;
  uint64_t batch_index = 0;  // which global batch this slot holds
  bool ready = false;        // filled by a worker, not yet consumed
  bool in_use = false;       // handed to the consumer, not yet released
};

uint64_t XorShift(uint64_t* s) {
  uint64_t x = *s;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  *s = x;
  return x * 0x2545F4914F6CDD1DULL;
}

struct Loader {
  // immutable after open
  int fd = -1;
  const uint8_t* base = nullptr;  // mmap of the payload
  size_t map_len = 0;
  uint64_t n_records = 0;      // records in THIS shard's universe
  uint64_t record_bytes = 0;
  uint64_t batch = 0;
  uint64_t batches_per_epoch = 0;
  int shuffle = 0;
  uint64_t seed = 0;
  uint64_t shard_index = 0;    // global record = local * shard_count + index
  uint64_t shard_count = 1;

  // epoch state (guarded by mu)
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits for its batch
  std::condition_variable cv_free;    // workers wait for a free slot
  std::vector<Slot> ring;
  std::vector<uint32_t> perm;         // current epoch's permutation
  uint64_t perm_epoch = ~0ULL;        // epoch `perm` belongs to
  uint64_t next_claim = 0;            // next global batch index to fill
  uint64_t next_deliver = 0;          // next global batch index to hand out
  bool stopping = false;

  std::vector<std::thread> workers;

  void EnsurePermLocked(uint64_t epoch) {
    if (perm_epoch == epoch) return;
    perm.resize(n_records);
    std::iota(perm.begin(), perm.end(), 0u);
    if (shuffle) {
      uint64_t s = seed * 0x9E3779B97F4A7C15ULL + epoch + 1;
      for (uint64_t i = n_records - 1; i > 0; --i) {
        uint64_t j = XorShift(&s) % (i + 1);
        std::swap(perm[i], perm[j]);
      }
    }
    perm_epoch = epoch;
  }

  void WorkerLoop() {
    std::vector<uint32_t> indices(batch);
    std::vector<uint8_t> staging(batch * record_bytes);
    for (;;) {
      uint64_t my_batch;
      {
        std::unique_lock<std::mutex> lk(mu);
        my_batch = next_claim++;
        uint64_t epoch = my_batch / batches_per_epoch;
        uint64_t in_epoch = my_batch % batches_per_epoch;
        // workers never run more than one epoch ahead of the permutation
        // they need; EnsurePermLocked regenerates when the epoch advances.
        // A worker claiming a batch of epoch E while another still fills
        // E-1 is fine: indices are copied out under the lock.
        EnsurePermLocked(epoch);
        for (uint64_t k = 0; k < batch; ++k)
          indices[k] = perm[in_epoch * batch + k];
        if (stopping) return;
      }
      // gather outside the lock: this is the expensive part
      for (uint64_t k = 0; k < batch; ++k) {
        uint64_t g = (uint64_t)indices[k] * shard_count + shard_index;
        memcpy(staging.data() + k * record_bytes,
               base + g * record_bytes, record_bytes);
      }
      {
        std::unique_lock<std::mutex> lk(mu);
        Slot* slot = &ring[my_batch % ring.size()];
        cv_free.wait(lk, [&] {
          return stopping || (!slot->ready && !slot->in_use &&
                              // slot's previous tenant must be delivered
                              my_batch < next_deliver + ring.size());
        });
        if (stopping) return;
        slot->data.swap(staging);
        slot->batch_index = my_batch;
        slot->ready = true;
        if (staging.size() != batch * record_bytes)
          staging.resize(batch * record_bytes);
        cv_ready.notify_all();
      }
    }
  }
};

}  // namespace

extern "C" {

// Returns a handle, or null on error (message to stderr).
void* adl_open_sharded(const char* path, uint64_t batch, int shuffle,
                       uint64_t seed, int num_threads, uint64_t ring_slots,
                       uint64_t shard_index, uint64_t shard_count) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    perror("adl_open");
    return nullptr;
  }
  uint8_t header[20];
  if (read(fd, header, 20) != 20 || memcmp(header, "ADT1", 4) != 0) {
    fprintf(stderr, "adl_open: %s is not an ADT1 record file\n", path);
    close(fd);
    return nullptr;
  }
  uint64_t n_records, record_bytes;
  memcpy(&n_records, header + 4, 8);
  memcpy(&record_bytes, header + 12, 8);
  if (batch == 0) {
    fprintf(stderr, "adl_open: batch must be > 0\n");
    close(fd);
    return nullptr;
  }
  if (shard_count == 0 || shard_index >= shard_count) {
    fprintf(stderr, "adl_open: shard %llu/%llu invalid\n",
            (unsigned long long)shard_index, (unsigned long long)shard_count);
    close(fd);
    return nullptr;
  }
  uint64_t n_global = n_records;
  // this shard's universe: strided records {i : i % count == index}
  n_records = n_global / shard_count +
              (shard_index < n_global % shard_count ? 1 : 0);
  if (n_records < batch) {
    fprintf(stderr, "adl_open: batch %llu > records %llu (shard %llu/%llu)\n",
            (unsigned long long)batch, (unsigned long long)n_records,
            (unsigned long long)shard_index, (unsigned long long)shard_count);
    close(fd);
    return nullptr;
  }
  if (n_records > UINT32_MAX) {
    // the epoch permutation stores uint32 indices; silently wrapping would
    // sample the wrong records
    fprintf(stderr,
            "adl_open: n_records %llu exceeds 2^32-1 (perm index width)\n",
            (unsigned long long)n_records);
    close(fd);
    return nullptr;
  }
  struct stat st;
  fstat(fd, &st);
  if (record_bytes == 0 ||
      n_global > (SIZE_MAX - 20) / record_bytes) {  // corrupt header
    fprintf(stderr, "adl_open: %s header overflows (n=%llu rb=%llu)\n", path,
            (unsigned long long)n_global, (unsigned long long)record_bytes);
    close(fd);
    return nullptr;
  }
  size_t want = 20 + n_global * record_bytes;  // the FULL file is mapped
  if ((size_t)st.st_size < want) {
    fprintf(stderr, "adl_open: %s truncated (%lld < %zu)\n", path,
            (long long)st.st_size, want);
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, want, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    perror("adl_open: mmap");
    close(fd);
    return nullptr;
  }
  auto* L = new Loader();
  L->fd = fd;
  L->base = (const uint8_t*)map + 20;
  L->map_len = want;
  L->n_records = n_records;
  L->record_bytes = record_bytes;
  L->batch = batch;
  L->batches_per_epoch = n_records / batch;
  L->shuffle = shuffle;
  L->seed = seed;
  L->shard_index = shard_index;
  L->shard_count = shard_count;
  if (ring_slots < 2) ring_slots = 2;
  L->ring.resize(ring_slots);
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i)
    L->workers.emplace_back([L] { L->WorkerLoop(); });
  return L;
}

void* adl_open(const char* path, uint64_t batch, int shuffle, uint64_t seed,
               int num_threads, uint64_t ring_slots) {
  return adl_open_sharded(path, batch, shuffle, seed, num_threads, ring_slots,
                          0, 1);
}

uint64_t adl_record_bytes(void* h) { return ((Loader*)h)->record_bytes; }
uint64_t adl_num_records(void* h) { return ((Loader*)h)->n_records; }
uint64_t adl_batches_per_epoch(void* h) {
  return ((Loader*)h)->batches_per_epoch;
}

// Blocks until the next in-order batch is ready; returns its buffer (valid
// until adl_release_batch) and writes the global batch index.
const uint8_t* adl_next_batch(void* h, uint64_t* batch_index_out) {
  auto* L = (Loader*)h;
  std::unique_lock<std::mutex> lk(L->mu);
  uint64_t want = L->next_deliver;
  Slot* slot = &L->ring[want % L->ring.size()];
  L->cv_ready.wait(lk, [&] {
    return L->stopping || (slot->ready && slot->batch_index == want);
  });
  if (L->stopping) return nullptr;
  slot->ready = false;
  slot->in_use = true;
  L->next_deliver = want + 1;
  if (batch_index_out) *batch_index_out = want;
  return slot->data.data();
}

void adl_release_batch(void* h, uint64_t batch_index) {
  auto* L = (Loader*)h;
  std::unique_lock<std::mutex> lk(L->mu);
  Slot* slot = &L->ring[batch_index % L->ring.size()];
  slot->in_use = false;
  L->cv_free.notify_all();
}

void adl_close(void* h) {
  auto* L = (Loader*)h;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->stopping = true;
    L->cv_ready.notify_all();
    L->cv_free.notify_all();
  }
  for (auto& t : L->workers) t.join();
  munmap((void*)(L->base - 20), L->map_len);
  close(L->fd);
  delete L;
}

}  // extern "C"
