// Coordination service — the native control-plane runtime.
//
// TPU-native replacement for the native surfaces the reference borrows from
// TensorFlow's C++ runtime (SURVEY §2.0): the per-node distributed gRPC
// server (reference autodist/utils/server_starter.py launches tf.Server),
// and the C++ ConditionalAccumulator / token-FIFOQueue kernels that
// implement PS sync barriers and bounded staleness
// (reference kernel/synchronization/ps_synchronizer.py:335-458).
//
// XLA owns the data plane (ICI/DCN collectives); what training jobs still
// need from a host-side service is exactly what those queues provided:
//   - job-wide named barriers            (sync PS step boundary)
//   - a key/value board                  (strategy-id / address exchange)
//   - per-worker step reports + MINSTEP  (bounded-staleness window:
//                                         proceed while my_step <= min+s)
//   - heartbeats + dead-worker detection (the Coordinator's fail-fast
//                                         watcher, reference coordinator.py:98-110)
//
// Design: single-threaded poll(2) event loop, newline-delimited text
// protocol, no dependencies. Blocking ops (BARRIER, WAITMIN) are handled by
// parking the reply until the condition fires — no server-side threads.
//
// Protocol (one command per line, space-separated):
//   PING                      -> PONG
//   PUT <key> <value>         -> OK
//   GET <key>                 -> VAL <value> | NONE
//   INC <name> [token]        -> VAL <n>              (atomic counter)
//   BARRIER <name> <n> [token] -> OK                  (blocks until n arrive)
//   STEP <worker> <step> [token] -> OK                (report progress)
//   MINSTEP                   -> VAL <min over workers>
//   WAITMIN <step> <stale>    -> OK                   (blocks until
//                                                      step <= minstep+stale)
//   HEARTBEAT <worker>        -> OK
//   GOODBYE <worker>          -> OK                   (clean deregister:
//                                                      drops heartbeat +
//                                                      step records so a
//                                                      finished worker is
//                                                      never counted dead
//                                                      and stops holding
//                                                      the staleness window)
//   DEADLIST <timeout_s>      -> VAL <w1,w2,...> | NONE
//   BPUT <key> <ver> <b64>    -> OK                   (versioned blob store:
//                                                      async-PS value serving)
//   BGET <key>                -> BVAL <ver> <b64> | NONE
//   QPUSH <q> <b64>           -> OK                   (FIFO blob queue:
//                                                      async-PS grad push)
//   QPOP <q>                  -> QVAL <b64> | NONE
//   QLEN <q>                  -> VAL <n>
//   SHUTDOWN                  -> OK (then exits)
//
// Idempotency tokens (round 6): the side-effecting commands INC, STEP,
// BARRIER, BPUTB and QPUSHB accept an optional trailing <token> argument
// (any whitespace-free string, client-generated, unique per LOGICAL
// operation). The service remembers the reply it produced for each token
// (bounded FIFO cache, kMaxTokens entries) and REPLAYS it for a repeated
// token without re-applying the command — so a client that retries after
// an ambiguous connection drop (request possibly applied, reply lost) can
// never double-apply a gradient blob, double-count a barrier arrival, or
// double-increment a counter. The dedup state lives in service memory:
// it survives any number of connection drops but NOT a service restart —
// consistent, since a restart also loses the counters/queues/blobs the
// tokens guarded. Read-only and naturally idempotent commands (GET,
// BGET*, QLEN, MINSTEP, WAITMIN, HEARTBEAT, PUT, GOODBYE) take no token:
// re-running them is always safe.
//
// Binary blob framing (round 4): the b64 text forms above cost +33% wire
// and an encode/decode pass on every gradient/value blob. The B-suffixed
// variants carry the payload as RAW bytes, length-prefixed by the header
// line (the control plane stays newline-delimited text):
//   BPUTB <key> <ver> <n> [token]\n<n raw bytes>  -> OK
//   BGETB <key>               -> BVALB <ver> <n>\n<n raw bytes> | NONE
//   QPUSHB <q> <n> [token]\n<n raw bytes>         -> OK | ERR queue full
//   QPOPB <q>                 -> QVALB <n>\n<n raw bytes> | NONE
// Blobs are stored raw either way; text and binary commands interoperate
// on the same keys/queues (text reads of binary-written blobs b64-encode
// on the way out).
//
// The blob commands are the wire of the ASYNC parameter-server path
// (autodist_tpu/runtime/ps_service.py): the owner publishes versioned
// parameter blobs with BPUT, workers fetch with BGET and push gradient
// blobs with QPUSH, and the owner's apply thread drains with QPOP — the
// role the reference's C++ ConditionalAccumulator + gRPC send/recv kernels
// played for async PS (reference ps_synchronizer.py:556-633). Payloads are
// base64 (the protocol stays newline-delimited text).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <deque>
#include <map>
#include <string>
#include <utility>
#include <vector>

namespace {

// Strict length parse: the whole token must be digits (optionally signed)
// and in range. atol() returns 0 for garbage like "x16" — which would
// accept a zero-byte frame and then parse the real payload as commands —
// and has undefined behavior on overflow.
bool ParseLen(const std::string& s, long* out) {
  if (s.empty()) return false;
  errno = 0;
  char* end = nullptr;
  long v = strtol(s.c_str(), &end, 10);
  if (errno == ERANGE || end != s.c_str() + s.size()) return false;
  *out = v;
  return true;
}

const char kB64[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string B64Encode(const std::string& in) {
  std::string out;
  out.reserve(((in.size() + 2) / 3) * 4);
  size_t i = 0;
  for (; i + 2 < in.size(); i += 3) {
    unsigned v = (static_cast<unsigned char>(in[i]) << 16) |
                 (static_cast<unsigned char>(in[i + 1]) << 8) |
                 static_cast<unsigned char>(in[i + 2]);
    out += kB64[(v >> 18) & 63]; out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63]; out += kB64[v & 63];
  }
  if (i < in.size()) {
    unsigned v = static_cast<unsigned char>(in[i]) << 16;
    bool two = i + 1 < in.size();
    if (two) v |= static_cast<unsigned char>(in[i + 1]) << 8;
    out += kB64[(v >> 18) & 63]; out += kB64[(v >> 12) & 63];
    out += two ? kB64[(v >> 6) & 63] : '=';
    out += '=';
  }
  return out;
}

std::string B64Decode(const std::string& in) {
  static int rev[256];
  static bool init = false;
  if (!init) {
    for (int i = 0; i < 256; ++i) rev[i] = -1;
    for (int i = 0; i < 64; ++i) rev[static_cast<unsigned char>(kB64[i])] = i;
    init = true;
  }
  std::string out;
  out.reserve((in.size() / 4) * 3);
  unsigned v = 0;
  int bits = 0;
  for (char c : in) {
    int d = rev[static_cast<unsigned char>(c)];
    if (d < 0) continue;  // '=' padding / whitespace
    v = (v << 6) | d;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out += static_cast<char>((v >> bits) & 0xFF);
    }
  }
  return out;
}

double NowSeconds() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

struct Waiter {
  int fd;
  // barrier waiter
  std::string barrier;
  // staleness waiter: proceed when step <= minstep + staleness
  bool is_waitmin = false;
  long step = 0;
  long staleness = 0;
};

struct Conn {
  int fd;
  std::string inbuf;
  std::string outbuf;
  size_t out_off = 0;  // sent prefix of outbuf (offset beats erase():
                       // an 8 MB blob would memmove itself per send)
  // binary framing: >0 while awaiting this many raw payload bytes for the
  // parked command below
  size_t bin_need = 0;
  std::vector<std::string> bin_args;
  // bytes of a *rejected* frame's payload still to drain: the client sends
  // header+payload in one write, so after an ERR the payload bytes are
  // already in flight and must not be parsed as command lines
  size_t bin_discard = 0;
  bool close_requested = false;  // length unparseable -> cannot resync
};

class Server {
 public:
  explicit Server(int port) : port_(port) {}

  int Run() {
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) { perror("socket"); return 1; }
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      perror("bind");
      return 1;
    }
    if (listen(listen_fd_, 128) < 0) { perror("listen"); return 1; }
    fprintf(stderr, "[coordination_service] listening on :%d\n", port_);
    fflush(stderr);
    EventLoop();
    return 0;
  }

 private:
  void EventLoop() {
    while (!shutdown_) {
      std::vector<pollfd> fds;
      fds.push_back({listen_fd_, POLLIN, 0});
      for (auto& [fd, conn] : conns_) {
        short events = POLLIN;
        if (conn.out_off < conn.outbuf.size()) events |= POLLOUT;
        fds.push_back({fd, events, 0});
      }
      int rc = poll(fds.data(), fds.size(), 1000);
      if (rc < 0 && errno != EINTR) { perror("poll"); break; }
      if (fds[0].revents & POLLIN) Accept();
      std::vector<int> closed;
      for (size_t i = 1; i < fds.size(); ++i) {
        int fd = fds[i].fd;
        auto it = conns_.find(fd);
        if (it == conns_.end()) continue;
        if (fds[i].revents & (POLLERR | POLLHUP)) {
          closed.push_back(fd);
          continue;
        }
        if (fds[i].revents & POLLIN) {
          if (!ReadFrom(it->second)) closed.push_back(fd);
        }
        if (fds[i].revents & POLLOUT) Flush(it->second);
      }
      for (int fd : closed) CloseConn(fd);
    }
  }

  void Accept() {
    int fd = accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
    conns_[fd] = Conn{fd, "", ""};
  }

  bool ReadFrom(Conn& conn) {
    char buf[262144];  // blob-sized reads: 4 KB would cost one syscall
                       // per 4 KB of a multi-MB gradient payload
    while (true) {
      ssize_t n = recv(conn.fd, buf, sizeof(buf), 0);
      if (n > 0) {
        conn.inbuf.append(buf, n);
      } else if (n == 0) {
        return false;  // peer closed
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        return false;
      }
    }
    while (true) {
      if (conn.bin_discard > 0) {
        size_t drop = std::min(conn.bin_discard, conn.inbuf.size());
        conn.inbuf.erase(0, drop);
        conn.bin_discard -= drop;
        if (conn.bin_discard > 0) break;  // more to drain on a later read
        continue;
      }
      if (conn.bin_need > 0) {
        if (conn.inbuf.size() < conn.bin_need) break;  // payload incomplete
        std::string payload = conn.inbuf.substr(0, conn.bin_need);
        conn.inbuf.erase(0, conn.bin_need);
        conn.bin_need = 0;
        HandleBinaryPayload(conn, std::move(payload));
        continue;
      }
      size_t pos = conn.inbuf.find('\n');
      if (pos == std::string::npos) break;
      std::string line = conn.inbuf.substr(0, pos);
      conn.inbuf.erase(0, pos + 1);
      if (!line.empty() && line.back() == '\r') line.pop_back();
      Handle(conn, line);
      if (conn.close_requested) break;
    }
    Flush(conn);
    return !conn.close_requested;
  }

  static std::vector<std::string> Split(const std::string& s) {
    std::vector<std::string> out;
    size_t i = 0;
    while (i < s.size()) {
      size_t j = s.find(' ', i);
      if (j == std::string::npos) j = s.size();
      if (j > i) out.push_back(s.substr(i, j - i));
      i = j + 1;
    }
    return out;
  }

  void Reply(Conn& conn, const std::string& msg) {
    conn.outbuf += msg;
    conn.outbuf += '\n';
  }

  void ReplyFd(int fd, const std::string& msg) {
    auto it = conns_.find(fd);
    if (it != conns_.end()) {
      Reply(it->second, msg);
      Flush(it->second);
    }
  }

  void Flush(Conn& conn) {
    while (conn.out_off < conn.outbuf.size()) {
      ssize_t n = send(conn.fd, conn.outbuf.data() + conn.out_off,
                       conn.outbuf.size() - conn.out_off, MSG_NOSIGNAL);
      if (n > 0) {
        conn.out_off += static_cast<size_t>(n);
      } else {
        return;  // EAGAIN or error; poll will retry / detect close
      }
    }
    conn.outbuf.clear();
    conn.out_off = 0;
  }

  // ---- idempotency-token dedup: replies keyed by client token, bounded
  //      FIFO eviction (kMaxTokens). Stored replies are the RAW outbuf
  //      bytes (newline included), so replay is a verbatim append.
  bool ReplayToken(Conn& conn, const std::string& tok) {
    if (tok.empty()) return false;
    auto it = token_replies_.find(tok);
    if (it == token_replies_.end()) return false;
    conn.outbuf += it->second;
    return true;
  }

  void RememberToken(const std::string& tok, const std::string& raw_reply) {
    if (tok.empty()) return;
    if (token_replies_.emplace(tok, raw_reply).second) {
      token_order_.push_back(tok);
      if (token_order_.size() > kMaxTokens) {
        token_replies_.erase(token_order_.front());
        token_order_.pop_front();
      }
    }
  }

  // execute-and-remember for immediate (non-parked) tokened commands:
  // the reply bytes the handler appends are captured as the token's
  // replay record
  void ReplyTokened(Conn& conn, const std::string& tok,
                    const std::string& msg) {
    Reply(conn, msg);
    RememberToken(tok, msg + "\n");
  }

  void Handle(Conn& conn, const std::string& line) {
    auto parts = Split(line);
    if (parts.empty()) return;
    const std::string& cmd = parts[0];
    if (cmd == "PING") {
      Reply(conn, "PONG");
    } else if (cmd == "PUT" && parts.size() >= 3) {
      // value may contain spaces: everything after the key
      size_t vpos = line.find(parts[1]) + parts[1].size() + 1;
      kv_[parts[1]] = line.substr(vpos);
      Reply(conn, "OK");
    } else if (cmd == "GET" && parts.size() == 2) {
      auto it = kv_.find(parts[1]);
      if (it == kv_.end()) Reply(conn, "NONE");
      else Reply(conn, "VAL " + it->second);
    } else if (cmd == "INC" && (parts.size() == 2 || parts.size() == 3)) {
      const std::string tok = parts.size() == 3 ? parts[2] : "";
      if (ReplayToken(conn, tok)) return;
      long v = ++counters_[parts[1]];
      ReplyTokened(conn, tok, "VAL " + std::to_string(v));
    } else if (cmd == "BARRIER" && (parts.size() == 3 || parts.size() == 4)) {
      const std::string& name = parts[1];
      long want = atol(parts[2].c_str());
      const std::string tok = parts.size() == 4 ? parts[3] : "";
      // a token that already fired replays OK immediately — the retried
      // arrival must NOT wait for peers who already passed the barrier
      if (ReplayToken(conn, tok)) return;
      auto& waiters = barrier_waiters_[name];
      // a retry whose ORIGINAL arrival is still parked (its dead
      // connection not yet reaped in this poll cycle) must REPLACE it,
      // not join it — one logical arrival, never two
      bool replaced = false;
      if (!tok.empty()) {
        for (auto& w : waiters) {
          if (w.second == tok) { w.first = conn.fd; replaced = true; break; }
        }
      }
      if (!replaced) waiters.push_back({conn.fd, tok});
      if (static_cast<long>(barrier_waiters_[name].size()) >= want) {
        for (auto& [fd, wtok] : barrier_waiters_[name]) {
          ReplyFd(fd, "OK");
          RememberToken(wtok, "OK\n");
        }
        barrier_waiters_.erase(name);
      }
    } else if (cmd == "STEP" && (parts.size() == 3 || parts.size() == 4)) {
      const std::string tok = parts.size() == 4 ? parts[3] : "";
      if (ReplayToken(conn, tok)) return;
      steps_[parts[1]] = atol(parts[2].c_str());
      ReplyTokened(conn, tok, "OK");
      WakeStaleWaiters();
    } else if (cmd == "MINSTEP") {
      Reply(conn, "VAL " + std::to_string(MinStep()));
    } else if (cmd == "WAITMIN" && parts.size() == 3) {
      long step = atol(parts[1].c_str());
      long stale = atol(parts[2].c_str());
      if (step <= MinStep() + stale) {
        Reply(conn, "OK");
      } else {
        stale_waiters_.push_back(Waiter{conn.fd, "", true, step, stale});
      }
    } else if (cmd == "HEARTBEAT" && parts.size() == 2) {
      heartbeats_[parts[1]] = NowSeconds();
      Reply(conn, "OK");
    } else if (cmd == "GOODBYE" && parts.size() == 2) {
      heartbeats_.erase(parts[1]);
      steps_.erase(parts[1]);
      Reply(conn, "OK");
      // the departed worker no longer bounds the staleness window
      WakeStaleWaiters();
    } else if (cmd == "DEADLIST" && parts.size() == 2) {
      double timeout = atof(parts[1].c_str());
      double now = NowSeconds();
      std::string dead;
      for (auto& [w, t] : heartbeats_) {
        if (now - t > timeout) {
          if (!dead.empty()) dead += ",";
          dead += w;
        }
      }
      Reply(conn, dead.empty() ? "NONE" : "VAL " + dead);
    } else if (cmd == "BPUT" && parts.size() == 4) {
      // storage is RAW bytes for both wire forms; the text form carries
      // b64 and converts at the boundary
      blobs_[parts[1]] = {atol(parts[2].c_str()), B64Decode(parts[3])};
      Reply(conn, "OK");
    } else if (cmd == "BGET" && parts.size() == 2) {
      auto it = blobs_.find(parts[1]);
      if (it == blobs_.end()) {
        Reply(conn, "NONE");
      } else {
        Reply(conn, "BVAL " + std::to_string(it->second.first) + " " +
                        B64Encode(it->second.second));
      }
    } else if (cmd == "QPUSH" && parts.size() == 3) {
      // cap: a queue nobody drains (dead owner) must not eat the host's
      // memory; clients see the rejection and fail loudly
      auto& q = queues_[parts[1]];
      if (q.size() >= kMaxQueueLen) {
        Reply(conn, "ERR queue full");
      } else {
        q.push_back(B64Decode(parts[2]));
        Reply(conn, "OK");
      }
    } else if (cmd == "QPOP" && parts.size() == 2) {
      auto it = queues_.find(parts[1]);
      if (it == queues_.end() || it->second.empty()) {
        Reply(conn, "NONE");
      } else {
        Reply(conn, "QVAL " + B64Encode(it->second.front()));
        it->second.pop_front();
      }
    } else if (cmd == "QLEN" && parts.size() == 2) {
      auto it = queues_.find(parts[1]);
      long n = (it == queues_.end()) ? 0 : static_cast<long>(it->second.size());
      Reply(conn, "VAL " + std::to_string(n));
    } else if (cmd == "BPUTB" && (parts.size() == 4 || parts.size() == 5)) {
      long n = 0;
      const std::string tok = parts.size() == 5 ? parts[4] : "";
      if (!ParseLen(parts[3], &n) || n < 0) {
        // length unparseable/negative -> the payload boundary is lost
        // (atol would return 0 for "x16" and the real payload would be
        // parsed as command lines); close rather than desync
        Reply(conn, "ERR bad length");
        conn.close_requested = true;
      } else if (n > kMaxBlobBytes) {
        // the client already sent header+payload in one write: drain
        // exactly n bytes so line parsing resumes at the next frame
        Reply(conn, "ERR bad length");
        conn.bin_discard = static_cast<size_t>(n);
      } else if (ReplayToken(conn, tok)) {
        // duplicate: replay the recorded reply, but the retried payload
        // bytes are already in flight and must still be drained
        conn.bin_discard = static_cast<size_t>(n);
      } else {
        conn.bin_args = {cmd, parts[1], parts[2], tok};
        conn.bin_need = static_cast<size_t>(n);
        if (conn.bin_need == 0) HandleBinaryPayload(conn, "");
      }
    } else if (cmd == "QPUSHB" && (parts.size() == 3 || parts.size() == 4)) {
      long n = 0;
      const std::string tok = parts.size() == 4 ? parts[3] : "";
      if (!ParseLen(parts[2], &n) || n < 0) {
        Reply(conn, "ERR bad length");
        conn.close_requested = true;
      } else if (n > kMaxBlobBytes) {
        Reply(conn, "ERR bad length");
        conn.bin_discard = static_cast<size_t>(n);
      } else if (ReplayToken(conn, tok)) {
        conn.bin_discard = static_cast<size_t>(n);
      } else {
        conn.bin_args = {cmd, parts[1], tok};
        conn.bin_need = static_cast<size_t>(n);
        if (conn.bin_need == 0) HandleBinaryPayload(conn, "");
      }
    } else if (cmd == "BGETB" && parts.size() == 2) {
      auto it = blobs_.find(parts[1]);
      if (it == blobs_.end()) {
        Reply(conn, "NONE");
      } else {
        Reply(conn, "BVALB " + std::to_string(it->second.first) + " " +
                        std::to_string(it->second.second.size()));
        conn.outbuf += it->second.second;  // raw, length-prefixed above
      }
    } else if (cmd == "QPOPB" && parts.size() == 2) {
      auto it = queues_.find(parts[1]);
      if (it == queues_.end() || it->second.empty()) {
        Reply(conn, "NONE");
      } else {
        Reply(conn, "QVALB " + std::to_string(it->second.front().size()));
        conn.outbuf += it->second.front();
        it->second.pop_front();
      }
    } else if (cmd == "SHUTDOWN") {
      Reply(conn, "OK");
      Flush(conn);
      shutdown_ = true;
    } else {
      Reply(conn, "ERR unknown command");
    }
  }

  void HandleBinaryPayload(Conn& conn, std::string payload) {
    std::vector<std::string> args;
    args.swap(conn.bin_args);
    if (args.empty()) return;
    if (args[0] == "BPUTB") {
      blobs_[args[1]] = {atol(args[2].c_str()), std::move(payload)};
      ReplyTokened(conn, args[3], "OK");
    } else if (args[0] == "QPUSHB") {
      auto& q = queues_[args[1]];
      if (q.size() >= kMaxQueueLen) {
        // remembered too: a retry of a rejected push must replay the
        // rejection, not sneak a second copy in once the queue drains
        ReplyTokened(conn, args[2], "ERR queue full");
      } else {
        q.push_back(std::move(payload));
        ReplyTokened(conn, args[2], "OK");
      }
    }
  }

  long MinStep() {
    long m = 0;
    bool first = true;
    for (auto& [w, s] : steps_) {
      if (first || s < m) { m = s; first = false; }
    }
    return m;
  }

  void WakeStaleWaiters() {
    long m = MinStep();
    std::vector<Waiter> still;
    for (auto& w : stale_waiters_) {
      if (w.step <= m + w.staleness) ReplyFd(w.fd, "OK");
      else still.push_back(w);
    }
    stale_waiters_.swap(still);
  }

  void CloseConn(int fd) {
    // drop from any barrier/staleness wait lists: a parked arrival whose
    // connection died is forgotten, so the client's tokened retry counts
    // as the (single) arrival
    for (auto& [name, waiters] : barrier_waiters_) {
      waiters.erase(std::remove_if(waiters.begin(), waiters.end(),
                                   [fd](const std::pair<int, std::string>& w) {
                                     return w.first == fd;
                                   }),
                    waiters.end());
    }
    std::vector<Waiter> still;
    for (auto& w : stale_waiters_)
      if (w.fd != fd) still.push_back(w);
    stale_waiters_.swap(still);
    close(fd);
    conns_.erase(fd);
  }

  int port_;
  int listen_fd_ = -1;
  bool shutdown_ = false;
  std::map<int, Conn> conns_;
  std::map<std::string, std::string> kv_;
  static constexpr size_t kMaxQueueLen = 4096;
  // binary-frame payload cap: far above any gradient blob, far below
  // anything that could park the parser / eat host memory
  static constexpr long kMaxBlobBytes = 1L << 31;  // 2 GB
  std::map<std::string, std::pair<long, std::string>> blobs_;
  std::map<std::string, std::deque<std::string>> queues_;
  std::map<std::string, long> counters_;
  // idempotency dedup: token -> raw reply bytes, FIFO-evicted. 64k
  // entries bound the memory; a token older than 64k subsequent tokened
  // RPCs can no longer be retried — far beyond any client retry window.
  static constexpr size_t kMaxTokens = 1 << 16;
  std::map<std::string, std::string> token_replies_;
  std::deque<std::string> token_order_;
  std::map<std::string, std::vector<std::pair<int, std::string>>>
      barrier_waiters_;
  std::vector<Waiter> stale_waiters_;
  std::map<std::string, long> steps_;
  std::map<std::string, double> heartbeats_;
};

}  // namespace

int main(int argc, char** argv) {
  signal(SIGPIPE, SIG_IGN);
  int port = argc > 1 ? atoi(argv[1]) : 15999;
  Server server(port);
  return server.Run();
}
