"""AutoDist entry point.

Analog of reference ``autodist/autodist.py``: the user-facing object tying
capture -> strategy build/load -> compile -> lowering -> execution together,
with the chief-vs-worker role split driven by the ``ADT_WORKER`` env var
(reference ``autodist.py:40-41``) and a one-instance-per-process registry
(reference ``autodist.py:43-57``).

Usage (the 3-line-change pattern of ``examples/linear_regression.py``):

    ad = AutoDist(resource_spec_file="spec.yml",
                  strategy_builder=strategy.PSLoadBalancing())
    train_step = ad.function(loss_fn, optimizer=opt, params=params,
                             example_batch=batch)
    for batch in data:
        metrics = train_step(batch)
"""
import contextlib
import json
import os
import time
from typing import Callable, Optional

from autodist_tpu import const, patch
from autodist_tpu.kernel.graph_transformer import GraphTransformer
from autodist_tpu.model_item import ModelItem
from autodist_tpu.parallel import mesh as mesh_lib
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.runtime.runner import Runner, WrappedSession
from autodist_tpu.strategy.base import Strategy, StrategyCompiler
from autodist_tpu.utils import logging

_DEFAULT_AUTODIST = {}


def set_default_autodist(obj):
    """One AutoDist instance per process (reference ``autodist.py:43-57``)."""
    if _DEFAULT_AUTODIST:
        raise NotImplementedError("Only one AutoDist instance per process is "
                                  "supported; call autodist_tpu.reset() in tests")
    _DEFAULT_AUTODIST[0] = obj


def get_default_autodist():
    return _DEFAULT_AUTODIST.get(0)


def reset():
    """Clear process-global state (for tests and sequential programmatic
    use; the reference isolates with fresh subprocesses instead,
    ``tests/integration/test_all.py:53-69``). Clearing the registry alone
    is not isolation — serving threads, coordination sockets, a capture
    context leaked by an exception mid-trace, and the optimizer-capture
    registry would all bleed into the next build, so reset tears each
    down."""
    inst = _DEFAULT_AUTODIST.get(0)
    _DEFAULT_AUTODIST.clear()  # clear FIRST: reset is the documented
    # recovery path and must work even when teardown (or a half-finished
    # __init__ that registered itself before failing) raises
    if inst is not None:
        try:
            inst.close()
        except AttributeError:
            pass  # __init__ failed before those attributes existed
    from autodist_tpu.ops import embedding
    embedding.clear_capture()
    patch.clear_captured()
    from autodist_tpu.telemetry import spans as _tspans
    _tspans.reset()  # drop recorded spans/counters, re-read ADT_TRACE
    from autodist_tpu.telemetry import blackbox as _bb
    _bb.reset()  # clear the flight recorder's event/log tails
    from autodist_tpu.runtime import elastic as _elastic
    _elastic.clear()  # drop the epoch-fenced membership (and its socket)
    from autodist_tpu.runtime import preemption as _preemption
    _preemption.reset()  # forget signal notices and armed guards


class AutoDist:
    def __init__(self, resource_spec_file: Optional[str] = None,
                 strategy_builder=None, resource_spec: Optional[ResourceSpec] = None,
                 backend: Optional[str] = None, tracing: bool = False,
                 validate: str = "warn"):
        if validate not in ("error", "warn", "off"):
            raise ValueError("validate must be 'error', 'warn' or 'off', "
                             "got %r" % (validate,))
        set_default_autodist(self)
        # pre-compile strategy verification mode (analysis/rules.py):
        # "error" raises StrategyVerificationError before any kernel sees
        # the plan, "warn" logs the diagnostics, "off" skips the pass
        self._validate = validate
        const.makedirs()
        # Worker processes join the JAX distributed runtime from the env the
        # Coordinator set — must happen before any device query.
        from autodist_tpu.runtime import server_starter
        server_starter.maybe_init_distributed()
        if resource_spec is not None:
            self._resource_spec = resource_spec
        elif resource_spec_file is not None:
            self._resource_spec = ResourceSpec(resource_spec_file)
        else:
            self._resource_spec = ResourceSpec.from_local()
        excluded = [a for a in
                    const.ENV.ADT_ELASTIC_EXCLUDE.val.split(",") if a]
        if excluded:
            # permanently-lost workers (sync-elastic reduced-world
            # restart): every process sees the same reduced spec, so the
            # chief builds the strategy for — and the workers join — the
            # smaller world
            self._resource_spec = self._resource_spec.without_nodes(excluded)
        if strategy_builder is None:
            from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing
            strategy_builder = PSLoadBalancing()  # default, as in reference autodist.py:70
        self._strategy_builder = strategy_builder
        self._backend = backend
        self._tracing = tracing
        self._runner: Optional[Runner] = None
        self._coordinator = None
        patch.patch_optax() if const.ENV.ADT_PATCH_OPTAX.val else None
        self._early_launch()

    def _early_launch(self):
        """Chief-launched multi-node jobs: launch the workers and join the
        distributed runtime NOW, at construction — before the user creates
        any jnp array. The chief's ``jax.distributed`` join blocks until
        every worker connects, and joining is impossible once the XLA
        backend is initialized, so the order is forced: preallocate the
        strategy id, launch workers (they relaunch this script; their own
        ``AutoDist()`` joins from the env), join, and only then let the
        user build — ``_setup`` ships the serialized strategy afterwards
        (workers wait in their strategy poll). The reference's analogous
        flow (``coordinator.py:46-110``) had no such constraint because TF
        servers were separate processes."""
        from autodist_tpu.runtime import server_starter
        if (self._resource_spec.is_single_node() or not const.is_chief()
                or const.ENV.ADT_EXTERNAL_LAUNCH.val
                or const.ENV.ADT_DEBUG_REMOTE.val
                or server_starter.initialized()):
            return
        import datetime
        sid = const.ENV.ADT_STRATEGY_ID.val or datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y%m%dT%H%M%S%f")
        # the build path reads the preset id from env when serializing
        os.environ[const.ENV.ADT_STRATEGY_ID.name_str] = sid
        from autodist_tpu.runtime.cluster import SSHCluster
        from autodist_tpu.runtime.coordinator import Coordinator
        cluster = SSHCluster(self._resource_spec)
        # the chief's own process count: worker processes get it from
        # worker_env; multi-process wiring on the chief (async-PS serving,
        # staleness pacing, mirror checks) reads the same env
        os.environ[const.ENV.ADT_NUM_PROCESSES.name_str] = str(
            cluster.num_processes)
        self._coordinator = Coordinator(sid, cluster)
        self._coordinator.launch_clients(copy_strategy=False)
        cluster.start()  # joins as process 0; returns once workers connect
        if const.ENV.ADT_ELASTIC.val > 0:
            # async workers heartbeat time-based (runner.py); the watchdog
            # turns silence-while-alive (deadlock) into a kill that the
            # process watcher answers with an elastic relaunch — or, for
            # sync-elastic jobs, with the whole-job restart. Sync workers
            # write no heartbeat records (a >timeout gap between lockstep
            # steps — long eval, slow data — would read as death), so for
            # them the watchdog is a no-op and a wedge surfaces as a
            # collective timeout -> process death -> the same recovery.
            self._coordinator.start_watchdog()
        # atexit runs LIFO: this must fire BEFORE cluster.terminate (the
        # registration inside start()) so a clean exit flags the watchers
        # before terminate's SIGTERM makes a trailing worker "die"
        import atexit
        atexit.register(self._coordinator.stop_watchdog)

    @property
    def resource_spec(self) -> ResourceSpec:
        return self._resource_spec

    @property
    def is_chief(self) -> bool:
        return const.is_chief()

    @contextlib.contextmanager
    def scope(self):
        """Capture scope (reference ``autodist.py:309-322``). In JAX capture
        is explicit (functions passed to ``build``), so the scope's job is
        optimizer-construction recording."""
        patch.patch_optax()
        yield self

    # ------------------------------------------------------------- build path

    def _verify_strategy(self, strategy: Strategy, item: ModelItem,
                         sentinel_policy=None):
        """Static verification BEFORE kernel transformation
        (``analysis/rules.py`` + the plan-level memory gate of
        ``analysis/memory.py``): whole failure classes — malformed
        partitioners, dangling PS destinations, sync/compressor
        mismatches, numerics-safety violations of the bf16 compute tier
        (ADT60x), and a projected per-device OOM against the chip's
        HBM capacity (ADT501) — surface here as typed diagnostics
        instead of ``ValueError``s deep in the lowering (or collective
        deadlocks / allocation failures at runtime)."""
        if self._validate == "off":
            return
        from autodist_tpu.analysis import verify
        from autodist_tpu.analysis.diagnostics import (
            Severity, StrategyVerificationError)
        diags = list(verify(strategy, item, self._resource_spec))
        # the registered rules already cover the ADT601/602 errors; the
        # numerics entry point adds the sentinel-aware warnings (ADT603
        # loss-tier, ADT604 sentinel-less half precision) that need the
        # resolved policy this build is actually arming
        from autodist_tpu.analysis.rules import verify_numerics
        seen = {(d.code, d.message) for d in diags}
        diags += [d for d in verify_numerics(
            strategy, item, self._resource_spec,
            sentinel_policy=sentinel_policy)
            if (d.code, d.message) not in seen]
        try:
            from autodist_tpu.analysis import memory as memory_lib
            diags += memory_lib.plan_memory_report(
                strategy, item, self._resource_spec)["diagnostics"]
        except Exception as e:  # noqa: BLE001 — the memory gate is
            # best-effort: a model the cost heuristics cannot trace must
            # not fail an otherwise-verifiable build
            logging.debug("plan-level memory gate skipped: %s", e)
        errors = [d for d in diags if d.severity >= Severity.ERROR]
        for d in diags:
            log = (logging.warning if d.severity >= Severity.WARNING
                   else logging.debug)
            log("strategy verifier: %s", d.format())
        if errors and self._validate == "error":
            raise StrategyVerificationError(errors)

    def _build_or_load_strategy(self, model_item: ModelItem) -> Strategy:
        """Chief builds+serializes; workers load by id
        (reference ``autodist.py:100-109``).

        Two handoff modes:

        - chief-launched (reference behavior): the chief serializes to disk,
          the Coordinator copies the file to each worker before launching it,
          and workers load by ``ADT_STRATEGY_ID``;
        - externally launched (``ADT_EXTERNAL_LAUNCH``, GKE/mpirun style —
          all processes start simultaneously): the strategy travels over a
          collective broadcast, which by construction cannot deliver a stale
          file from a previous run sharing the same serialization dir. A
          preset ``ADT_STRATEGY_ID`` pins the id for reproducibility.
        """
        external = (const.ENV.ADT_EXTERNAL_LAUNCH.val
                    and const.ENV.ADT_NUM_PROCESSES.val > 1)
        if const.is_chief():
            strategy = self._strategy_builder.build(model_item, self._resource_spec)
            preset_id = const.ENV.ADT_STRATEGY_ID.val
            if preset_id:
                strategy.id = preset_id
            path = strategy.serialize()
            logging.info("built strategy %s -> %s", strategy.id, path)
            if external:
                from autodist_tpu.runtime import server_starter
                import jax
                if jax.process_index() != 0:
                    raise RuntimeError(
                        "externally-launched jobs must start the chief (no "
                        "ADT_WORKER) with ADT_PROCESS_ID=0; this chief is "
                        "process %d" % jax.process_index())
                server_starter.broadcast_bytes(
                    json.dumps(strategy.to_dict()).encode())
            return strategy
        if external:
            from autodist_tpu.runtime import server_starter
            data = server_starter.broadcast_bytes()
            return Strategy.from_dict(json.loads(data.decode()))
        strategy_id = const.ENV.ADT_STRATEGY_ID.val
        if not strategy_id:
            raise RuntimeError("worker process missing ADT_STRATEGY_ID")
        # chief-launched workers start BEFORE the strategy exists (the
        # chief must launch + join the runtime before it can trace), so
        # this poll bounds the chief's whole build + the file copy — the
        # default must absorb a large model's trace/compile time
        wait_s = float(os.environ.get("ADT_STRATEGY_WAIT_S", "600"))
        deadline = time.monotonic() + wait_s
        while True:
            try:
                return Strategy.deserialize(strategy_id)
            except (FileNotFoundError, json.JSONDecodeError):
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        "strategy %s not available after %.0fs; did the "
                        "chief fail before serializing?"
                        % (strategy_id, wait_s))
                time.sleep(0.2)

    def _setup(self, strategy: Strategy):
        """Chief-only: bring up the cluster + launch worker clients
        (reference ``autodist.py:120-128``). Single-node runs skip this, as
        do externally-launched jobs — their workers already exist, so
        SSH-launching clients would register duplicate process ids with the
        running jax.distributed job."""
        if self._coordinator is not None:
            # chief-launched flow: workers were launched (and the runtime
            # joined) at construction; now that the strategy exists on
            # disk, ship it — the workers are waiting in their poll
            self._coordinator.distribute_strategy()
            return
        if (self._resource_spec.is_single_node() or not const.is_chief()
                or const.ENV.ADT_EXTERNAL_LAUNCH.val):
            return
        from autodist_tpu.runtime.coordinator import Coordinator
        from autodist_tpu.runtime.cluster import SSHCluster
        cluster = SSHCluster(self._resource_spec)
        self._coordinator = Coordinator(strategy, cluster)
        cluster.start()
        self._coordinator.launch_clients()

    def build(self, loss_fn: Callable, optimizer, params, example_batch,
              has_aux: bool = False, apply_fn: Optional[Callable] = None,
              trainable_filter: Optional[Callable] = None,
              mp_rules=None, mp_meta=None, sentinel=None) -> Runner:
        """Capture + compile + lower; returns a Runner (uninitialized).
        ``mp_rules`` (e.g. ``models.tp_lm.tp_rules()``) registers the
        model's model-parallel sharding map so AutoStrategy searches the
        TP/PP/EP space too; ``mp_meta`` carries the search hints
        (pp_microbatches, pp_schedules, seq_parallel). ``sentinel``
        arms the training health sentinel (``runtime/sentinel.py``):
        ``None`` defers to ``ADT_SENTINEL``, ``True`` uses the default
        :class:`~autodist_tpu.runtime.sentinel.SentinelPolicy`, a policy
        instance is used as-is — health guards are then compiled INTO
        the step program (docs/sentinel.md)."""
        from autodist_tpu.runtime.sentinel import resolve_policy
        policy = resolve_policy(sentinel)
        item = ModelItem(loss_fn=loss_fn, optimizer=optimizer, params=params,
                         example_batch=example_batch, has_aux=has_aux,
                         apply_fn=apply_fn,
                         trainable_filter=trainable_filter,
                         mp_rules=mp_rules, mp_meta=mp_meta).prepare()
        strategy = self._build_or_load_strategy(item)
        self._verify_strategy(strategy, item, sentinel_policy=policy)
        compiled = StrategyCompiler(item, self._resource_spec).compile(strategy)
        logging.info("compiled %r", compiled)
        logging.debug("compiled strategy:\n%s", compiled)
        # pipeline knobs are baked into the loss at model-build time; a
        # strategy claiming different ones (an AutoStrategy alternate from
        # mp_meta) would be priced/gated for a program that never runs —
        # or, for interleaved pp_shards, train a DIFFERENT logical layer
        # order than every unbound trace emulates. Fail with the rebuild
        # instruction instead.
        meta = item.mp_meta or {}
        gc = compiled.graph_config
        picked_checks = [
            ("pp_schedule", gc.pp_schedule, "schedule"),
            ("pp_microbatches", gc.pp_microbatches, "n_microbatches"),
            ("pp_virtual", gc.pp_virtual, "virtual_stages"),
            ("pp_shards",
             (gc.mesh_shape or {}).get(const.PIPELINE_AXIS), "pp_shards"),
        ]
        for key, picked, setup_kw in picked_checks:
            declared = meta.get(key)
            if key == "pp_shards" and meta.get("pp_schedule") != "interleaved":
                # gpipe/1f1b losses read S off the mesh axis at run time;
                # only the interleaved loss bakes the stage count
                continue
            if (declared is not None and picked is not None
                    and declared != picked):
                raise ValueError(
                    "the strategy wants pipeline %s=%r but the loss was "
                    "built with %r — rebuild the model's loss "
                    "(make_train_setup(%s=%r)) and declare it via "
                    "mp_meta[%r]"
                    % (key, picked, declared, setup_kw, picked, key))
        self._setup(compiled)
        is_async = self._validate_async(compiled, item)
        if (const.ENV.ADT_ELASTIC.val > 0 and not is_async
                and const.ENV.ADT_NUM_PROCESSES.val > 1):
            # sync strategies are collective-lockstep: a relaunched worker
            # cannot rejoin mid-run, so elastic means checkpoint-restore
            # orchestration — worker death tears the whole mesh down and
            # the chief re-execs with auto-resume (the coordinator's
            # _restart_whole_job). Auto-resume needs periodic saves:
            # Runner.fit(save_every=...) or explicit Saver.save calls.
            if not const.ENV.ADT_ELASTIC_SYNC.val:
                raise ValueError(
                    "ADT_ELASTIC on a sync strategy needs "
                    "ADT_ELASTIC_SYNC=1 at bring-up (the jax.distributed "
                    "join was skipped for the async-elastic flow and "
                    "cannot happen retroactively). Set ADT_ELASTIC_SYNC=1 "
                    "for whole-job checkpoint-restore recovery, or use an "
                    "async host-PS strategy (e.g. PS(sync=False))")
            if self._coordinator is not None:
                self._coordinator.enable_sync_elastic()
            logging.info(
                "ADT_ELASTIC on a sync strategy: whole-job checkpoint-"
                "restore recovery enabled (resume dir: %s)",
                const.ENV.ADT_CKPT_DIR.val)
        if (is_async and const.ENV.ADT_ELASTIC.val > 0
                and const.ENV.ADT_ELASTIC_SYNC.val):
            raise ValueError(
                "ADT_ELASTIC_SYNC is set but the strategy is async PS: "
                "unset it — async elastic restarts workers individually "
                "and must not pin the process set with jax.distributed")
        if is_async:
            # async PS cannot ride global collectives (they are lockstep):
            # each process runs its OWN local mesh — the reference's
            # between-graph replication — and couples to peers only through
            # the parameter service (runtime/ps_service.py)
            mesh = mesh_lib.local_mesh(backend=self._backend)
        else:
            mesh = mesh_lib.mesh_from_strategy(compiled, self._resource_spec,
                                               backend=self._backend)
        dstep = GraphTransformer(compiled, mesh, item,
                                 sentinel=policy).transform()
        if is_async and dstep.ps_store is not None:
            self._wire_async_ps(dstep)
        # in-run elastic (runtime/elastic.py): install the epoch-fenced
        # membership BEFORE the Runner exists (it binds to it at
        # construction) and keep the build inputs for the reconfigure
        # handler's mesh/program rebuild
        inrun = const.ENV.ADT_ELASTIC_INRUN.val and not is_async
        if inrun:
            self._arm_inrun_elastic(compiled)
        self._runner = Runner(
            dstep, tracing=self._tracing,
            hbm_budget_bytes=self._resource_spec.chip_hbm_bytes(),
            sentinel=policy if policy is not None else False)
        if inrun:
            self._last_build = {"strategy": compiled, "item": item,
                                "policy": policy}
            self._runner.set_reconfigure_handler(self._elastic_reconfigure)
        return self._runner

    def _arm_inrun_elastic(self, strategy):
        """Install this process's epoch-fenced membership (chief publishes
        the launch epoch; workers read it — or already carry one from the
        grow-on-join admission). Also lints the topology up front: an
        ADT430 job can never shrink in-run, so say so at build time, not
        at the first death."""
        from autodist_tpu.analysis import rules as rules_lib
        from autodist_tpu.runtime import elastic, preemption
        # single-node jobs never construct a Coordinator, so the loud
        # knob validation must also run here
        elastic.validate_elastic_knobs()
        preemption.validate_preempt_knobs()
        for d in rules_lib.verify_elastic(strategy):
            logging.warning("elastic: %s", d.format())
        # the planned-handoff path rides the in-run shrink, so arming it
        # on a fail-fast (model-parallel) family warns at build time
        for d in rules_lib.verify_preemption(strategy):
            logging.warning("preemption: %s", d.format())
        if elastic.current() is not None:
            return  # admitted via grow-on-join: membership already live
        self._orig_spec = self._resource_spec
        roster = elastic.roster_layout(
            list(self._resource_spec.node_addresses),
            self._resource_spec.chief)
        worker = const.ENV.ADT_WORKER.val or self._resource_spec.chief
        epoch = 1
        membership = elastic.Membership(worker, epoch, roster)
        try:
            if const.is_chief():
                info = membership._with_client(elastic.read_epoch)
                if info is None:
                    membership._with_client(
                        lambda c: elastic.publish_epoch(c, 1, roster))
                else:
                    membership.adopt(*info)
            else:
                info = membership.peek()
                if info is not None:
                    membership.adopt(*info)
        except OSError as e:
            logging.warning("elastic: coordination service unreachable "
                            "(%s); membership starts at the launch epoch",
                            e)
        elastic.install(membership)
        logging.info("elastic: in-run membership armed — %s at epoch %d "
                     "(roster %s)", worker, membership.epoch,
                     ",".join(membership.roster))

    def _elastic_reconfigure(self, runner, epoch, roster, snapshot):
        """The rebuild half of an in-run reconfiguration (the Runner's
        ``_maybe_reconfigure`` drives the protocol half): re-join the
        process set as the epoch's roster, rebuild mesh + programs for the
        new world, and re-place the state — from the in-memory snapshot
        when every shard had a live local replica, else from the last-good
        checkpoint (PR 8's re-shard path). On a grow, the chief broadcasts
        the snapshot so the joiner adopts the run's truth."""
        from autodist_tpu.runtime import elastic
        membership = elastic.current()
        grew = (membership is not None
                and len(roster) > len(membership.roster))
        orig = getattr(self, "_orig_spec", self._resource_spec)
        excluded = [a for a in orig.node_addresses if a not in roster]
        spec = orig.without_nodes(excluded) if excluded else orig
        info = self._last_build
        # topology gate BEFORE any teardown, with EXACTLY verify_elastic's
        # rule (size-1 model axes are degenerate data-parallel and fine):
        # the coordinator's shrink decision and this handler must never
        # disagree, and a refusal here must leave the old process set
        # intact so the whole-job escalation can still run
        mesh_shape = dict(info["strategy"].graph_config.mesh_shape or {})
        if any(ax != const.DATA_AXIS and int(n) > 1
               for ax, n in mesh_shape.items()):
            raise RuntimeError(
                "in-run reconfigure reached a model-parallel strategy "
                "(ADT430 should have refused the shrink): mesh axes %s"
                % mesh_shape)
        self._resource_spec = spec
        # tear down + re-join jax.distributed as the new process set
        if self._coordinator is not None:
            self._coordinator._cluster.reconfigure(roster, epoch)
        else:
            elastic.rejoin_process_set(roster, epoch, chief=orig.chief)
        # rebuild mesh and programs over the survivors' devices: the data
        # axis resizes to whatever the NEW world exposes (the strategy's
        # recorded replica list names the launch world's devices);
        # degenerate size-1 model axes are preserved so the programs'
        # axis names keep resolving
        if mesh_shape:
            import jax as _jax
            mesh_shape[const.DATA_AXIS] = len(_jax.devices(self._backend)
                                              if self._backend
                                              else _jax.devices())
            mesh = mesh_lib.build_mesh(axes=mesh_shape,
                                       backend=self._backend)
        else:
            mesh = mesh_lib.build_mesh(backend=self._backend)
        dstep = GraphTransformer(info["strategy"], mesh, info["item"],
                                 sentinel=info["policy"]).transform()
        runner.adopt_distributed_step(dstep)
        if snapshot is None:
            # some shard had no live local replica (dead PS owner /
            # cross-process sharding): fall back to the last-good
            # checkpoint's cross-topology re-shard
            from autodist_tpu.checkpoint import latest_checkpoint
            found, saver = latest_checkpoint(const.ENV.ADT_CKPT_DIR.val)
            if saver is None:
                raise RuntimeError(
                    "elastic reconfigure: state is not locally "
                    "reconstructible and no committed checkpoint exists "
                    "in %s" % const.ENV.ADT_CKPT_DIR.val)
            saver.restore(runner)
            logging.warning("elastic: re-sharded from checkpoint step %s "
                            "(no live replica for some state)", found)
            if grew:
                snapshot = elastic.snapshot_runner_state(runner)
        if grew and len(roster) > 1:
            snapshot = elastic.broadcast_state(snapshot)
        if snapshot is not None:
            elastic.adopt_snapshot(runner, snapshot)

    def build_step(self, step_fn: Callable, state, example_batch,
                   sentinel=None) -> Runner:
        """Opaque-step capture mode: distribute a hand-written
        ``step_fn(state, batch) -> (new_state, metrics)`` by assigning
        strategy-derived shardings (state leaves get their layout's pspec,
        the batch splits over the data axis) — no gradient interception,
        so AllReduce/Partitioned families only (host-PS and compressors
        need :meth:`build`'s loss_fn mode). ``state`` is the user's whole
        training state (params + optimizer state bundled however they
        like); the framework never looks inside the step. A ``sentinel``
        policy degrades to host-side loss monitoring here (the opaque
        step hides its gradients — ADT420)."""
        from autodist_tpu.runtime.sentinel import resolve_policy
        policy = resolve_policy(sentinel)
        item = ModelItem(step_fn=step_fn, params=state,
                         example_batch=example_batch).prepare()
        strategy = self._build_or_load_strategy(item)
        self._verify_strategy(strategy, item, sentinel_policy=policy)
        compiled = StrategyCompiler(item, self._resource_spec).compile(strategy)
        logging.info("compiled %r (step_fn mode)", compiled)
        if self._validate_async(compiled, item):
            raise ValueError("async host-PS strategies cannot lower an "
                             "opaque step_fn — use loss_fn mode")
        self._setup(compiled)
        mesh = mesh_lib.mesh_from_strategy(compiled, self._resource_spec,
                                           backend=self._backend)
        dstep = GraphTransformer(compiled, mesh, item,
                                 sentinel=policy).transform()
        self._runner = Runner(
            dstep, tracing=self._tracing,
            hbm_budget_bytes=self._resource_spec.chip_hbm_bytes(),
            sentinel=policy if policy is not None else False)
        return self._runner

    def _validate_async(self, compiled: Strategy, item: ModelItem) -> bool:
        """True when the strategy requests async PS; async must be PURE
        host-PS (every trainable var, no proxy, no model-parallel mesh) —
        anything else would need a cross-process collective, which async
        training cannot have."""
        from autodist_tpu.parallel import ps as ps_lib
        plans = ps_lib.plan_host_ps(compiled, item.var_infos)
        if not any(not p.sync for p in plans.values()):
            return False
        missing = set(item.trainable_var_names) - set(plans)
        if missing:
            raise ValueError(
                "async PS (sync=False) requires EVERY trainable var on the "
                "no-proxy PS path; not PS-host-resident: %s" % sorted(missing))
        still_sync = sorted(n for n, p in plans.items() if p.sync)
        if still_sync:
            raise ValueError(
                "async PS is all-or-nothing: these vars request sync=True "
                "but the job is async (their deterministic mirror-apply "
                "semantics cannot be honored): %s" % still_sync)
        stale = sorted(n for n, p in plans.items() if p.staleness > 0)
        if stale:
            raise ValueError(
                "staleness is a SYNC-training window (coordination-service "
                "pacing); async PS always reads the latest published "
                "version — drop staleness on: %s" % stale)
        if compiled.graph_config.mesh_shape:
            raise ValueError("async PS cannot combine with model-parallel "
                             "mesh axes (collectives are lockstep)")
        return True

    def _wire_async_ps(self, dstep):
        """Attach the parameter service: single-process jobs use the
        in-process service; multi-process jobs talk to the chief's native
        coordination service (which async REQUIRES)."""
        from autodist_tpu.runtime import ps_service as pss
        my_host = const.ENV.ADT_WORKER.val or self._resource_spec.chief
        if const.ENV.ADT_NUM_PROCESSES.val <= 1:
            services = {}

            def service_for_host(host):
                return services.setdefault(host, pss.LocalPSService())
        else:
            from autodist_tpu.runtime.coordination import CoordinationClient
            from autodist_tpu.runtime.resilience import (
                ResilientCoordinationClient)
            coord_host = (const.ENV.ADT_COORDINATOR_ADDR.val.split(":")[0]
                          or self._resource_spec.chief)
            port = const.ENV.ADT_COORDSVC_PORT.val
            try:
                CoordinationClient(coord_host, port).ping()
            except OSError as e:
                raise RuntimeError(
                    "async PS requires the native coordination service at "
                    "%s:%d (%s)" % (coord_host, port, e))

            # resilient clients: per-RPC deadlines + reconnect/backoff +
            # idempotency-token dedup, so a transient service blip or a
            # dropped connection never double-applies a gradient blob nor
            # wedges a serving thread forever (runtime/resilience.py;
            # failure model in docs/failure_model.md)
            def service_for_host(host):
                return pss.CoordPSService(
                    lambda: ResilientCoordinationClient(coord_host, port),
                    prefix="ps:" + host)
        dstep.ps_store.enable_serving(service_for_host, my_host)

    def close(self):
        """Tear down everything this instance started: the runner's
        coordination clients, the host-PS store's serving threads and
        service sockets, and the coordinator's watchers. Called by
        ``autodist_tpu.reset()``; safe to call twice."""
        runner = getattr(self, "_runner", None)
        if runner is not None:
            runner.close()
            self._runner = None
        coordinator = getattr(self, "_coordinator", None)
        if coordinator is not None:
            coordinator.stop_watchdog()

    def function(self, loss_fn: Callable, *, optimizer, params, example_batch=None,
                 has_aux: bool = False) -> Callable:
        """TF2-style stepping function (reference ``autodist.py:269-289``):
        lazily builds on first call (using that call's batch as the example),
        then every call runs one distributed step and returns host metrics."""
        box = {}

        def stepper(batch):
            if "runner" not in box:
                ex = example_batch if example_batch is not None else batch
                runner = self.build(loss_fn, optimizer, params, ex, has_aux)
                runner.init(params)
                box["runner"] = runner
            return box["runner"].run(batch)

        stepper.get_runner = lambda: box.get("runner")
        return stepper

    def create_distributed_session(self, loss_fn=None, optimizer=None, params=None,
                                   example_batch=None, has_aux: bool = False) -> WrappedSession:
        """Session facade (reference ``autodist.py:191-198``)."""
        if self._runner is None:
            if loss_fn is None:
                raise ValueError("no model built; pass loss_fn/optimizer/params")
            runner = self.build(loss_fn, optimizer, params, example_batch, has_aux)
            runner.init(params)
        return WrappedSession(self._runner)

    @property
    def runner(self) -> Optional[Runner]:
        return self._runner
