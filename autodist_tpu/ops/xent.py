"""Memory-lean softmax cross-entropy for big-vocab LM heads.

The standard head materializes ``logits [N, V]`` in fp32 (lm1b: 32x256
tokens x 99k vocab = 3.25 GB) plus softmax residuals for the backward —
the tensor that decides the biggest batch a chip fits. Here neither the
forward nor the backward ever holds more than one ``[N, C]`` vocab chunk:

- forward: one ``lax.scan`` over vocab chunks maintains the online
  logsumexp (running max + normalizer, same trick as flash attention's
  softmax) and picks out each token's target logit as its chunk passes.
- backward (custom_vjp): recomputes each chunk's logits from the saved
  activations (linear — one matmul), forms ``softmax - onehot`` for that
  chunk only, and accumulates dx / per-chunk dW, db slices.

Peak extra memory: ``N*C`` floats (134 MB at C=4096 for the lm1b shape)
instead of ``N*V`` — what lets lm1b train at batch 64 on a 16 GB v5e.
Exact same math as ``log_softmax`` + gather to float tolerance
(tests/test_xent.py).
"""
import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _num_chunks(vocab: int, chunk: int) -> int:
    return (vocab + chunk - 1) // chunk


def _pad_wb(w, b, chunk):
    """Pad the vocab dim to a chunk multiple with NEG_INF bias (padded
    logits then never win the max and add ~0 to the normalizer)."""
    v = w.shape[1]
    pad = _num_chunks(v, chunk) * chunk - v
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
        b = jnp.pad(b, (0, pad), constant_values=NEG_INF)
    return w, b


def _chunked(w, b, chunk):
    """(w_chunks [n, D, C], b_chunks [n, C]) — the ONE place that defines
    the chunk layout; forward and backward must agree on which weight
    slice each scan iteration sees."""
    wp, bp = _pad_wb(w, b, chunk)
    nchunks = wp.shape[1] // chunk
    w_chunks = wp.reshape(wp.shape[0], nchunks, chunk).transpose(1, 0, 2)
    b_chunks = bp.reshape(nchunks, chunk)
    return w_chunks, b_chunks, nchunks


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def chunked_softmax_xent(x, w, b, targets, chunk=8192):
    """Per-token negative log-likelihood of ``targets`` under the linear
    head ``x @ w + b``, never materializing the full [N, V] logits.

    x: [N, D] activations; w: [D, V]; b: [V]; targets: [N] int32.
    Returns nll [N] float32. Differentiable in x, w, b.
    """
    nll, _ = _xent_fwd_impl(x, w, b, targets, chunk)
    return nll


def _xent_fwd_impl(x, w, b, targets, chunk):
    n, _d = x.shape
    # clamp like take_along_axis in the standard path: an out-of-vocab
    # id must not silently yield nll = lse (tgt stuck at its 0.0 init)
    targets = jnp.clip(targets, 0, w.shape[1] - 1)
    w_chunks, b_chunks, nchunks = _chunked(w, b, chunk)
    xf = x.astype(jnp.float32)

    def body(carry, inputs):
        m, l, tgt = carry
        wc, bc, ci = inputs
        logits = (jax.lax.dot(xf, wc.astype(jnp.float32))
                  + bc.astype(jnp.float32)[None, :])         # [N, C]
        m_cur = jnp.max(logits, axis=1)
        m_new = jnp.maximum(m, m_cur)
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        # target logit if the target falls inside this chunk
        off = ci * chunk
        local = targets - off
        inside = (local >= 0) & (local < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=1)[:, 0]
        tgt = jnp.where(inside, picked, tgt)
        return (m_new, l, tgt), None

    m0 = jnp.full((n,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    t0 = jnp.zeros((n,), jnp.float32)
    (m, l, tgt), _ = jax.lax.scan(
        body, (m0, l0, t0),
        (w_chunks, b_chunks, jnp.arange(nchunks)))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    nll = lse - tgt
    return nll, (x, w, b, targets, lse)


def _xent_fwd(x, w, b, targets, chunk):
    return _xent_fwd_impl(x, w, b, targets, chunk)


def _xent_bwd(chunk, res, g):
    """g: cotangent [N]. d_nll/d_logit = softmax - onehot(target); each
    chunk's logits are recomputed from the saved activations."""
    x, w, b, targets, lse = res
    n, d = x.shape
    v = w.shape[1]
    targets = jnp.clip(targets, 0, v - 1)  # mirror the forward's clamp
    w_chunks, b_chunks, nchunks = _chunked(w, b, chunk)
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)

    def body(dx, inputs):
        wc, bc, ci = inputs
        logits = (jax.lax.dot(xf, wc.astype(jnp.float32))
                  + bc.astype(jnp.float32)[None, :])
        p = jnp.exp(logits - lse[:, None])                  # softmax chunk
        off = ci * chunk
        local = targets - off
        inside = (local >= 0) & (local < chunk)
        onehot = (jnp.clip(local, 0, chunk - 1)[:, None]
                  == jnp.arange(chunk)[None, :]) & inside[:, None]
        dlog = (p - onehot.astype(p.dtype)) * gf[:, None]   # [N, C]
        dx = dx + jax.lax.dot(dlog, wc.astype(jnp.float32).T)
        dwc = jax.lax.dot(xf.T, dlog)                       # [D, C]
        dbc = jnp.sum(dlog, axis=0)
        return dx, (dwc, dbc)

    dx0 = jnp.zeros((n, d), jnp.float32)
    dx, (dw_chunks, db_chunks) = jax.lax.scan(
        body, dx0, (w_chunks, b_chunks, jnp.arange(nchunks)))
    dw = dw_chunks.transpose(1, 0, 2).reshape(d, nchunks * chunk)[:, :v]
    db = db_chunks.reshape(nchunks * chunk)[:v]
    return (dx.astype(x.dtype), dw.astype(w.dtype), db.astype(b.dtype),
            None)


chunked_softmax_xent.defvjp(_xent_fwd, _xent_bwd)
