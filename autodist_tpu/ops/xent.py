"""Memory-lean softmax cross-entropy for big-vocab LM heads.

The standard head materializes ``logits [N, V]`` in fp32 (lm1b: 32x256
tokens x 99k vocab = 3.25 GB) plus softmax residuals for the backward —
the tensor that decides the biggest batch a chip fits. Here neither the
forward nor the backward ever holds more than one ``[N, C]`` vocab chunk:

- forward: one ``lax.scan`` over vocab chunks maintains the online
  logsumexp (running max + normalizer, same trick as flash attention's
  softmax) and picks out each token's target logit as its chunk passes.
- backward (custom_vjp): recomputes each chunk's logits from the saved
  activations (linear — one matmul), forms ``softmax - onehot`` for that
  chunk only, and accumulates dx and in-place dW/db slices.

The weight matrix is never copied or padded: each scan step reads its
chunk with ``lax.dynamic_slice`` directly from ``w`` (a ragged final
chunk re-reads the tail at a clamped offset with the overlap masked
dead). Peak extra memory is the one ``[N, C]`` logits chunk — 268 MB at
the default C=8192 for lm1b's 8192 tokens, vs the 3.25 GB full logits.
Exact same math as ``log_softmax`` + gather to float tolerance
(tests/test_xent.py), including out-of-vocab targets (clamped, like
``take_along_axis``).
"""
import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _layout(v: int, chunk: int):
    """(effective chunk, number of chunks). The final chunk of a ragged
    vocab is read at the clamped offset ``v - chunk`` and its overlap
    with the previous chunk is masked dead — no padded weight copy."""
    chunk = min(chunk, v)
    return chunk, (v + chunk - 1) // chunk


def _chunk_view(w, b, ci, chunk, v):
    """This iteration's weight/bias slice read IN PLACE from w/b, plus
    the dead-column mask for the clamped final chunk.

    Returns (wc [D, C] fp32, bc [C] fp32, start, dead [C] bool) where
    ``dead`` marks columns already covered by the previous chunk."""
    off = ci * chunk
    start = jnp.minimum(off, v - chunk)
    wc = jax.lax.dynamic_slice_in_dim(w, start, chunk, axis=1)
    bc = jax.lax.dynamic_slice_in_dim(b, start, chunk, axis=0)
    cols = start + jnp.arange(chunk)
    dead = cols < off
    return (wc.astype(jnp.float32), bc.astype(jnp.float32), start, dead)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def chunked_softmax_xent(x, w, b, targets, chunk=8192):
    """Per-token negative log-likelihood of ``targets`` under the linear
    head ``x @ w + b``, never materializing the full [N, V] logits.

    x: [N, D] activations; w: [D, V]; b: [V]; targets: [N] int32.
    Returns nll [N] float32. Differentiable in x, w, b.

    Precision: the chunk matmuls run at the backend's DEFAULT matmul
    precision — the same as the standard full-logits head, so the two
    heads are comparable — which on TPU means bf16 passes (~1e-2
    absolute nll deviation from a float32 softmax reference; exact to
    ~1e-6 on float32 backends). Wrap the call in
    ``jax.default_matmul_precision('highest')`` when bit-level parity
    with an fp32 reference matters more than head throughput.
    """
    nll, _ = _xent_fwd_impl(x, w, b, targets, chunk)
    return nll


def _xent_fwd_impl(x, w, b, targets, chunk):
    n, _d = x.shape
    v = w.shape[1]
    # clamp like take_along_axis in the standard path: an out-of-vocab
    # id must not silently yield nll = lse (tgt stuck at its 0.0 init)
    targets = jnp.clip(targets, 0, v - 1)
    chunk, nchunks = _layout(v, chunk)
    xf = x.astype(jnp.float32)

    def body(carry, ci):
        m, l, tgt = carry
        wc, bc, start, dead = _chunk_view(w, b, ci, chunk, v)
        logits = jax.lax.dot(xf, wc) + bc[None, :]           # [N, C]
        logits = jnp.where(dead[None, :], NEG_INF, logits)
        m_cur = jnp.max(logits, axis=1)
        m_new = jnp.maximum(m, m_cur)
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        # target logit if the target falls inside this chunk's LIVE range
        local = targets - start
        inside = (targets >= ci * chunk) & (local < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=1)[:, 0]
        tgt = jnp.where(inside, picked, tgt)
        return (m_new, l, tgt), None

    m0 = jnp.full((n,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    t0 = jnp.zeros((n,), jnp.float32)
    (m, l, tgt), _ = jax.lax.scan(body, (m0, l0, t0), jnp.arange(nchunks))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    nll = lse - tgt
    return nll, (x, w, b, targets, lse)


def _xent_fwd(x, w, b, targets, chunk):
    return _xent_fwd_impl(x, w, b, targets, chunk)


def _xent_bwd(chunk, res, g):
    """g: cotangent [N]. d_nll/d_logit = softmax - onehot(target); each
    chunk's logits are recomputed from the saved activations, and dW/db
    accumulate into their slices in place (read-add-write inside the
    scan — dead overlap columns contribute exactly zero)."""
    x, w, b, targets, lse = res
    n, d = x.shape
    v = w.shape[1]
    targets = jnp.clip(targets, 0, v - 1)  # mirror the forward's clamp
    chunk, nchunks = _layout(v, chunk)
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)

    def body(carry, ci):
        dx, dw, db = carry
        wc, bc, start, dead = _chunk_view(w, b, ci, chunk, v)
        logits = jax.lax.dot(xf, wc) + bc[None, :]
        logits = jnp.where(dead[None, :], NEG_INF, logits)
        p = jnp.exp(logits - lse[:, None])                  # softmax chunk
        local = targets - start
        inside = (targets >= ci * chunk) & (local < chunk)
        onehot = (jnp.clip(local, 0, chunk - 1)[:, None]
                  == jnp.arange(chunk)[None, :]) & inside[:, None]
        dlog = (p - onehot.astype(p.dtype)) * gf[:, None]   # [N, C]
        dx = dx + jax.lax.dot(dlog, wc.T)
        dwc = jax.lax.dot(xf.T, dlog).astype(dw.dtype)      # [D, C]
        dbc = jnp.sum(dlog, axis=0).astype(db.dtype)
        dw = jax.lax.dynamic_update_slice_in_dim(
            dw, jax.lax.dynamic_slice_in_dim(dw, start, chunk, 1) + dwc,
            start, axis=1)
        db = jax.lax.dynamic_update_slice_in_dim(
            db, jax.lax.dynamic_slice_in_dim(db, start, chunk, 0) + dbc,
            start, axis=0)
        return (dx, dw, db), None

    dx0 = jnp.zeros((n, d), jnp.float32)
    dw0 = jnp.zeros((d, v), w.dtype)
    db0 = jnp.zeros((v,), b.dtype)
    (dx, dw, db), _ = jax.lax.scan(body, (dx0, dw0, db0),
                                   jnp.arange(nchunks))
    return (dx.astype(x.dtype), dw, db, None)


chunked_softmax_xent.defvjp(_xent_fwd, _xent_bwd)
