"""Attention ops, including sequence-parallel (ring / Ulysses) variants.

Long-context scaling is first-class here (the reference is data-parallel
only — SURVEY §5 "long-context: absent"): these ops let attention run with
the *sequence* dimension sharded across the mesh's ``seq`` axis.

- ``ring_attention``: blockwise attention with online (flash-style) softmax
  accumulation; K/V blocks rotate around the ring via ``ppermute`` so each
  device only ever holds one remote block — memory O(seq/N), comms ride
  nearest-neighbor ICI links (Liu et al., Ring Attention, arXiv 2310.01889).
- ``ulysses_attention``: all-to-all reshard seq-sharded -> head-sharded,
  run ordinary attention per head group, all-to-all back (DeepSpeed Ulysses,
  arXiv 2309.14509). Cheaper than ring when heads >= mesh axis and the
  all-to-all fits ICI.

Both are numerically exact (not approximations) and verified against the
reference attention in ``tests/test_sequence_parallel.py``.

All functions expect to run INSIDE shard_map with the given axis bound;
tensors are local chunks shaped [batch, seq_chunk, heads, head_dim].
"""
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def reference_attention(q, k, v, mask=None):
    """Plain softmax attention. [B, S, H, D] -> [B, S, H, D].
    mask: broadcastable to [B, H, Sq, Sk], True = attend."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    weights = jax.nn.softmax(logits.astype(jnp.float32)).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _block_update(q, k_blk, v_blk, acc, m, l, blk_mask, scale):
    """One online-softmax accumulation step (the flash-attention recurrence)."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
    if blk_mask is not None:
        logits = jnp.where(blk_mask, logits, -jnp.inf)
    m_blk = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # rows with no allowed keys yet keep m=-inf; guard the exp
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
    return acc_new, m_new, l_new


@partial(jax.named_call, name="ring_attention")
def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = False):
    """Exact attention over a sequence sharded along ``axis_name``.

    q, k, v: local chunks [B, C, H, D] (C = global_seq / axis_size), chunk r
    holding global positions [r*C, (r+1)*C). Returns the local output chunk.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_rank = jax.lax.axis_index(axis_name)
    B, C, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    q_pos = my_rank * C + jnp.arange(C)                      # global q positions

    acc0 = jnp.zeros((B, H, C, D), jnp.float32)
    m0 = jnp.full((B, H, C), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, C), jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def block_step(t, acc, m, l, k_cur, v_cur):
        # after t forward rotations, we hold the block originally at rank - t
        src = (my_rank - t) % axis_size
        blk_mask = None
        if causal:
            k_pos = src * C + jnp.arange(C)
            blk_mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
        return _block_update(q, k_cur, v_cur, acc, m, l, blk_mask, scale)

    def body(t, carry):
        acc, m, l, k_cur, v_cur = carry
        acc, m, l = block_step(t, acc, m, l, k_cur, v_cur)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    # N-1 rotations suffice: the last block updates WITHOUT the trailing
    # ppermute pair whose rotated result nothing reads (1/N of the op's
    # communication on an N-way ring)
    acc, m, l, k_last, v_last = jax.lax.fori_loop(
        0, axis_size - 1, body, (acc0, m0, l0, k, v))
    acc, m, l = block_step(axis_size - 1, acc, m, l, k_last, v_last)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


@partial(jax.named_call, name="ulysses_attention")
def ulysses_attention(q, k, v, axis_name: str = "seq", causal: bool = False,
                      mask: Optional[jax.Array] = None):
    """Ulysses sequence parallelism: all-to-all from seq-sharded to
    head-sharded, full-sequence attention on H/N heads, all-to-all back.

    Requires H % axis_size == 0. Local inputs [B, C, H, D] with C = S/N.
    """
    axis_size = jax.lax.psum(1, axis_name)
    B, C, H, D = q.shape
    if H % axis_size != 0:
        raise ValueError("ulysses needs heads %% axis_size == 0 (H=%d)" % H)

    def seq_to_heads(x):
        # [B, C, H, D] -> all_to_all over head dim -> [B, S, H/N, D]
        x = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                               tiled=True)
        return x

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    S = qg.shape[1]
    attn_mask = mask
    if causal:
        cm = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, None]
        attn_mask = cm if attn_mask is None else (attn_mask & cm)
    out = reference_attention(qg, kg, vg, attn_mask)
    return heads_to_seq(out)


def cached_attention(q, k_cache, v_cache, cursor):
    """Decode-shape attention against a KV cache (continuous batching).

    One query per slot against the slot's cached keys/values:
    ``q`` is [B, H, D] (the current token's projected query), ``k_cache``
    and ``v_cache`` are [B, T, H, D] slot caches, ``cursor`` is [B] int32
    — the row the current token was just written to. Rows ``<= cursor``
    are live; later rows hold garbage from evicted sequences and are
    masked out, which is what makes slot reuse safe without zeroing the
    cache. Numerics match :func:`reference_attention` on the live prefix
    (same fp32 softmax), so decode is exact-parity with full-sequence
    recompute (tests/test_decode.py)."""
    T = k_cache.shape[1]
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bhd,bthd->bht", q, k_cache) * scale
    mask = jnp.arange(T)[None, None, :] <= cursor[:, None, None]
    logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    weights = weights.astype(q.dtype)
    return jnp.einsum("bht,bthd->bhd", weights, v_cache)


def flash_cached_attention(q, k_cache, v_cache, cursor,
                           block_k: int = 128):
    """Decode-shape attention through the pallas flash kernel
    (``ops/flash_attention.py``) — the optional decode inner loop.

    The kernel tiles query blocks of at least 8 rows, so the single
    decode query is broadcast to an 8-row block and the cursor mask is
    expressed as segment ids (q row 0 gets segment 1; cache rows
    ``<= cursor`` get segment 1, dead rows 0): attention is allowed iff
    the segments match, which is exactly the live-prefix mask. Rows 1-7
    of the query block attend only dead rows and are discarded. Off-TPU
    the kernel runs under ``interpret=True``; when the cache length
    cannot be tiled the kernel itself falls back to the XLA reference
    path, so this is always safe to call.

    Parity with :func:`cached_attention` is allclose, not bitwise: the
    kernel accumulates blockwise in fp32 with a finite ``NEG_INF`` mask
    stand-in (tolerances documented in tests/test_decode.py)."""
    from autodist_tpu.ops.flash_attention import flash_attention
    B, T = k_cache.shape[0], k_cache.shape[1]
    q_blk = jnp.broadcast_to(q[:, None], (B, 8) + q.shape[1:])
    q_seg = jnp.zeros((B, 8), jnp.int32).at[:, 0].set(1)
    kv_seg = (jnp.arange(T)[None, :] <= cursor[:, None]).astype(jnp.int32)
    out = flash_attention(q_blk, k_cache, v_cache, causal=False,
                          segment_ids=(q_seg, kv_seg),
                          block_q=8, block_k=min(block_k, T))
    return out[:, 0]


def make_attn_fn(kind: str = "ring", axis_name: str = "seq",
                 causal: bool = False):
    """Attention implementation injectable into model layers
    (``models/layers.py`` MultiHeadAttention.attn_fn)."""
    if kind == "ring":
        def ring_fn(q, k, v, mask=None):
            if mask is not None:
                # silently dropping the model's padding mask would let
                # every token attend PAD positions with no error
                raise ValueError(
                    "ring attention cannot apply a dense mask (the K/V "
                    "blocks rotate); use kind='ulysses' (full-sequence "
                    "attention per head group honors masks) or pack "
                    "sequences without padding")
            return ring_attention(q, k, v, axis_name, causal=causal)
        return ring_fn
    if kind == "ulysses":
        return lambda q, k, v, mask=None: ulysses_attention(
            q, k, v, axis_name, causal=causal, mask=mask)
    if kind == "flash":
        # single-device fused pallas kernel (no mesh axis involved)
        from autodist_tpu.ops.flash_attention import make_flash_attn_fn
        return make_flash_attn_fn(causal=causal)
    if kind == "reference":
        return lambda q, k, v, mask=None: reference_attention(q, k, v, mask)
    raise ValueError("unknown attention kind %r" % kind)
