"""Pallas TPU flash attention: fused, tiled, memory-linear exact attention.

The reference has no kernel like this (its attention lives inside stock TF
ops); on TPU the fused softmax-attention kernel is the single hottest op in
every transformer benchmark (BERT / lm1b families, SURVEY §2.2), so it gets
a hand-written pallas kernel: O(seq) memory instead of the O(seq^2) logits
tensor XLA materializes, online-softmax accumulation in VMEM, matmuls on
the MXU in fp32 accumulation.

Design (standard FlashAttention-2 tiling, arXiv 2307.08691):
- forward: grid (batch, heads, q_blocks, kv_blocks) with the kv dimension
  innermost/"arbitrary"; running (m, l, acc) live in VMEM scratch across kv
  steps; the log-sum-exp per row is written out for the backward pass.
- backward: delta = rowsum(dO * O) precomputed in XLA (cheap elementwise),
  then two kernels — dQ over (q_blocks, kv_blocks) and dK/dV over
  (kv_blocks, q_blocks) — recompute P = exp(S - lse) tile by tile instead
  of storing it.
- causal: fully-masked tiles are skipped at trace time via ``pl.when``
  (upper-triangular tiles cost nothing), partial tiles are masked with
  broadcasted iotas.

On non-TPU backends the same kernels run under ``interpret=True`` so unit
tests exercise the identical code path on CPU (tests/test_flash_attention.py
checks fwd+grad against ``ops.attention.reference_attention``).

Layout matches the rest of the model zoo: [batch, seq, heads, head_dim].
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite stand-in for -inf: keeps exp() NaN-free on masked rows
_LANES = 128     # last-dim tile width; m/l scratch are lane-replicated


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(seq: int, want: int) -> int:
    """Largest power-of-two block <= want that divides seq (0 if none >= 8)."""
    b = min(want, seq)
    while b & (b - 1):
        b &= b - 1  # round down to a power of two (seq == b could be odd)
    while b >= 8 and seq % b:
        b //= 2
    return b if b >= 8 else 0


def _causal_mask_val(s, qi, ki, bq, bk):
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(rows >= cols, s, NEG_INF)


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, bq, bk, n_kv):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # under causal masking, tiles strictly above the diagonal are all-masked
    live = (qi * bq + bq - 1 >= ki * bk) if causal else True

    @pl.when(live)
    def _():
        q, k, v = q_ref[0, 0, :, :], k_ref[0, 0, :, :], v_ref[0, 0, :, :]
        # native-dtype (bf16) MXU operands, fp32 accumulation
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask_val(s, qi, ki, bq, bk)
        m_prev = m_ref[:, :1]                            # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)        # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                           # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                   # [bq, 1]
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == n_kv - 1)
    def _():
        l = l_ref[:, :1]
        o_ref[0, 0, :, :] = (acc_ref[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = m_ref[:, :1] + jnp.log(jnp.maximum(l, 1e-30))


def _fwd(q, k, v, causal, block_q, block_k):
    """q, k, v in [B, H, S, D] (kernel-internal layout)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _pick_block(Sq, block_q), _pick_block(Sk, block_k)
    scale = float(1.0 / np.sqrt(D))
    n_q, n_kv = Sq // bq, Sk // bk
    grid = (B, H, n_q, n_kv)

    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                           memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                            memory_space=pltpu.VMEM)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, n_kv=n_kv),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[q_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((B, H, Sq, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------- backward

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, scale, causal, bq, bk, n_kv):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    live = (qi * bq + bq - 1 >= ki * bk) if causal else True

    @pl.when(live)
    def _():
        q, k, v = q_ref[0, 0, :, :], k_ref[0, 0, :, :], v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]                        # [bq, 1]
        delta = delta_ref[0, 0, :, :]                    # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask_val(s, qi, ki, bq, bk)
        p = jnp.exp(s - lse)                             # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == n_kv - 1)
    def _():
        dq_ref[0, 0, :, :] = acc_ref[:].astype(dq_ref.dtype)


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, bq, bk, n_q):
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = (qi * bq + bq - 1 >= ki * bk) if causal else True

    @pl.when(live)
    def _():
        q, k, v = q_ref[0, 0, :, :], k_ref[0, 0, :, :], v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask_val(s, qi, ki, bq, bk)
        p = jnp.exp(s - lse).astype(do.dtype)            # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (jnp.exp(s - lse) * (dp - delta) * scale).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(causal, block_q, block_k, res, do):
    """res tensors in [B, H, S, D]; do arrives/leaves in [B, S, H, D]."""
    q, k, v, out, lse = res
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _pick_block(Sq, block_q), _pick_block(Sk, block_k)
    scale = float(1.0 / np.sqrt(D))
    n_q, n_kv = Sq // bq, Sk // bk
    do = do.transpose(0, 2, 1, 3)

    # delta_i = rowsum(dO_i * O_i): tiny elementwise reduce, XLA fuses it
    delta = jnp.einsum("bhsd,bhsd->bhs", do.astype(jnp.float32),
                       out.astype(jnp.float32))[..., None]

    params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))

    q_spec_i = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    kv_spec_j = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                             memory_space=pltpu.VMEM)
    row_spec_i = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                              memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, n_kv=n_kv),
        grid=(B, H, n_q, n_kv),
        in_specs=[q_spec_i, kv_spec_j, kv_spec_j, q_spec_i, row_spec_i,
                  row_spec_i],
        out_specs=q_spec_i,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=params,
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # kv-major grid: q is the reduction (innermost) dim
    q_spec_j = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, j, 0),
                            memory_space=pltpu.VMEM)
    kv_spec_i = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, i, 0),
                             memory_space=pltpu.VMEM)
    row_spec_j = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, j, 0),
                              memory_space=pltpu.VMEM)

    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, n_q=n_q),
        grid=(B, H, n_kv, n_q),
        in_specs=[q_spec_j, kv_spec_i, kv_spec_i, q_spec_j, row_spec_j,
                  row_spec_j],
        out_specs=[kv_spec_i, kv_spec_i],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=params,
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


# ---------------------------------------------------------------- public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    out, _ = _fwd(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                  v.transpose(0, 2, 1, 3), causal, block_q, block_k)
    return out.transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out, lse = _fwd(qt, kt, vt, causal, block_q, block_k)
    return out.transpose(0, 2, 1, 3), (qt, kt, vt, out, lse)


def _flash_bwd(causal, block_q, block_k, res, do):
    return _bwd(causal, block_q, block_k, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _tileable(q, k, block_q, block_k):
    return bool(_pick_block(q.shape[1], block_q)) and \
        bool(_pick_block(k.shape[1], block_k))


def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = 128, block_k: int = 128):
    """Exact fused attention. q,k,v: [B, S, H, D] -> [B, S, H, D].

    Falls back to the XLA reference path (differentiable as usual) when the
    sequence can't be tiled (remainder below the 8-row minimum block)."""
    if not _tileable(q, k, block_q, block_k):
        from autodist_tpu.ops.attention import reference_attention
        mask = None
        if causal:
            rows = jnp.arange(q.shape[1])[:, None]
            cols = jnp.arange(k.shape[1])[None, :]
            mask = (rows >= cols)[None, None]
        return reference_attention(q, k, v, mask)
    return _flash(q, k, v, causal, block_q, block_k)


def make_flash_attn_fn(causal: bool = True, block_q: int = 128,
                       block_k: int = 128):
    """(q, k, v, mask) -> out adapter for model layers' ``attn_fn`` slot.
    The mask slot must be unused — causality is handled in-kernel."""
    def attn(q, k, v, mask=None):
        if mask is not None:
            raise ValueError("flash attention handles causality in-kernel; "
                             "pass mask=None and set causal=")
        return flash_attention(q, k, v, causal, block_q, block_k)
    return attn
