"""Pallas TPU flash attention: fused, tiled, memory-linear exact attention.

The reference has no kernel like this (its attention lives inside stock TF
ops); on TPU the fused softmax-attention kernel is the single hottest op in
every transformer benchmark (BERT / lm1b families, SURVEY §2.2), so it gets
a hand-written pallas kernel: O(seq) memory instead of the O(seq^2) logits
tensor XLA materializes, online-softmax accumulation in VMEM, matmuls on
the MXU in fp32 accumulation.

Design (standard FlashAttention-2 tiling, arXiv 2307.08691):
- forward: grid (batch, heads, q_blocks, kv_blocks) with the kv dimension
  innermost/"arbitrary"; running (m, l, acc) live in VMEM scratch across kv
  steps; the log-sum-exp per row is written out for the backward pass.
- backward: delta = rowsum(dO * O) precomputed in XLA (cheap elementwise),
  then two kernels — dQ over (q_blocks, kv_blocks) and dK/dV over
  (kv_blocks, q_blocks) — recompute P = exp(S - lse) tile by tile instead
  of storing it.
- causal: fully-masked tiles are skipped at trace time via ``pl.when``
  (upper-triangular tiles cost nothing), partial tiles are masked with
  broadcasted iotas.
- segment ids (BERT padding masks, packed sequences): attention is allowed
  iff ``q_seg[i] == kv_seg[j]``. Tiles whose q-segment range cannot
  intersect the kv-segment range are skipped dynamically (``pl.when`` on a
  range-overlap test — exact skips for the sorted/contiguous layouts BERT
  and sequence packing produce, safe over-approximation otherwise);
  partial tiles are masked elementwise. A query whose segment matches NO
  key anywhere (possible only with a distinct ``(q_seg, kv_seg)`` pair —
  self-attention position i always sees position i) outputs zeros with
  zero gradients, guarded in both passes; the XLA fallback's softmax
  instead yields a uniform average for such rows, so don't rely on
  empty-row values across paths.

On non-TPU backends the same kernels run under ``interpret=True`` so unit
tests exercise the identical code path on CPU (tests/test_flash_attention.py
checks fwd+grad against ``ops.attention.reference_attention``).

Layout matches the rest of the model zoo: [batch, seq, heads, head_dim];
segment ids are [batch, seq] int32.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed TPUCompilerParams -> CompilerParams in jax 0.6; accept both so
# the kernels compile across the supported version range
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")

NEG_INF = -1e30  # finite stand-in for -inf: keeps exp() NaN-free on masked rows
_LANES = 128     # last-dim tile width; m/l scratch are lane-replicated


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(seq: int, want: int) -> int:
    """Largest power-of-two block <= want that divides seq (0 if none >= 8)."""
    b = min(want, seq)
    while b & (b - 1):
        b &= b - 1  # round down to a power of two (seq == b could be odd)
    while b >= 8 and seq % b:
        b //= 2
    return b if b >= 8 else 0


def _mask_val(s, qi, ki, bq, bk, causal, qs, ks):
    """Apply causal and/or segment masking to a score tile [bq, bk]."""
    if causal:
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    if qs is not None:
        s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
    return s


def _tile_live(qi, ki, bq, bk, causal, qs, ks):
    """Skip condition: False only when the tile provably has no visible
    entry. Causal skips are static (upper-triangular tiles); segment skips
    compare the blocks' id ranges (exact for sorted segments, safe
    over-approximation otherwise)."""
    live = (qi * bq + bq - 1 >= ki * bk) if causal else True
    if qs is not None:
        overlap = ((jnp.max(qs) >= jnp.min(ks))
                   & (jnp.min(qs) <= jnp.max(ks)))
        live = jnp.logical_and(live, overlap)
    return live


# ---------------------------------------------------------------- forward

def _fwd_kernel(*refs, scale, causal, has_seg, bq, bk, n_kv):
    if has_seg:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        qs_ref = ks_ref = None
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qs = qs_ref[0, :, 0] if has_seg else None
    ks = ks_ref[0, :, 0] if has_seg else None
    live = _tile_live(qi, ki, bq, bk, causal, qs, ks)

    @pl.when(live)
    def _():
        q, k, v = q_ref[0, 0, :, :], k_ref[0, 0, :, :], v_ref[0, 0, :, :]
        # native-dtype (bf16) MXU operands, fp32 accumulation
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask_val(s, qi, ki, bq, bk, causal, qs, ks)
        m_prev = m_ref[:, :1]                            # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)        # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                           # [bq, bk]
        # a row with NO visible key so far has m_new == NEG_INF and every
        # score masked: exp(NEG_INF - NEG_INF) = 1 would average garbage
        # values into the row — zero its contribution (empty rows emit 0)
        p = jnp.where(m_new > NEG_INF * 0.5, p, 0.0)
        corr = jnp.exp(m_prev - m_new)                   # [bq, 1]
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == n_kv - 1)
    def _():
        l = l_ref[:, :1]
        o_ref[0, 0, :, :] = (acc_ref[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # empty rows (l == 0) record lse = 0, NOT NEG_INF + log(1e-30):
        # the backward pass computes p = exp(s - lse), and a huge-negative
        # lse would blow exp() up to garbage gradients for those rows;
        # with lse = 0, exp(NEG_INF - 0) = 0 and the row's grads vanish
        lse_ref[0, 0, :, :] = jnp.where(
            l > 0, m_ref[:, :1] + jnp.log(jnp.maximum(l, 1e-30)), 0.0)


def _seg_specs(bq, bk, q_major=True):
    """BlockSpecs for segment-id arrays, carried as [B, S, 1] so the block
    trailing dims (rows, 1) satisfy the TPU (8, 128)-divisibility rule
    (same trick as the lse row vectors)."""
    if q_major:
        qs = pl.BlockSpec((1, bq, 1), lambda b, h, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
        ks = pl.BlockSpec((1, bk, 1), lambda b, h, i, j: (b, j, 0),
                          memory_space=pltpu.VMEM)
    else:  # kv-major grid (dk/dv kernel): i indexes kv, j indexes q
        qs = pl.BlockSpec((1, bq, 1), lambda b, h, i, j: (b, j, 0),
                          memory_space=pltpu.VMEM)
        ks = pl.BlockSpec((1, bk, 1), lambda b, h, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    return qs, ks


def _fwd(q, k, v, segs, causal, block_q, block_k):
    """q, k, v in [B, H, S, D] (kernel-internal layout); segs is None or
    (q_seg [B, Sq], kv_seg [B, Sk]) int32."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _pick_block(Sq, block_q), _pick_block(Sk, block_k)
    scale = float(1.0 / np.sqrt(D))
    n_q, n_kv = Sq // bq, Sk // bk
    grid = (B, H, n_q, n_kv)
    has_seg = segs is not None

    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                           memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [q, k, v]
    if has_seg:
        qs_spec, ks_spec = _seg_specs(bq, bk)
        in_specs += [qs_spec, ks_spec]
        operands += [segs[0].astype(jnp.int32)[..., None],
                     segs[1].astype(jnp.int32)[..., None]]

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, bq=bq, bk=bk, n_kv=n_kv),
        grid=grid,
        in_specs=in_specs,
        out_specs=[q_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((B, H, Sq, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*operands)
    return out, lse


# ---------------------------------------------------------------- backward

def _dq_kernel(*refs, scale, causal, has_seg, bq, bk, n_kv):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dq_ref, acc_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, acc_ref) = refs
        qs_ref = ks_ref = None
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    qs = qs_ref[0, :, 0] if has_seg else None
    ks = ks_ref[0, :, 0] if has_seg else None
    live = _tile_live(qi, ki, bq, bk, causal, qs, ks)

    @pl.when(live)
    def _():
        q, k, v = q_ref[0, 0, :, :], k_ref[0, 0, :, :], v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]                        # [bq, 1]
        delta = delta_ref[0, 0, :, :]                    # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask_val(s, qi, ki, bq, bk, causal, qs, ks)
        p = jnp.exp(s - lse)                             # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == n_kv - 1)
    def _():
        dq_ref[0, 0, :, :] = acc_ref[:].astype(dq_ref.dtype)


def _dkdv_kernel(*refs, scale, causal, has_seg, bq, bk, n_q):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qs_ref = ks_ref = None
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    qs = qs_ref[0, :, 0] if has_seg else None
    ks = ks_ref[0, :, 0] if has_seg else None
    live = _tile_live(qi, ki, bq, bk, causal, qs, ks)

    @pl.when(live)
    def _():
        q, k, v = q_ref[0, 0, :, :], k_ref[0, 0, :, :], v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask_val(s, qi, ki, bq, bk, causal, qs, ks)
        p = jnp.exp(s - lse).astype(do.dtype)            # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (jnp.exp(s - lse) * (dp - delta) * scale).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(causal, block_q, block_k, res, do):
    """res tensors in [B, H, S, D]; do arrives/leaves in [B, S, H, D]."""
    q, k, v, out, lse, q_seg, kv_seg = res
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _pick_block(Sq, block_q), _pick_block(Sk, block_k)
    scale = float(1.0 / np.sqrt(D))
    n_q, n_kv = Sq // bq, Sk // bk
    has_seg = q_seg is not None
    do = do.transpose(0, 2, 1, 3)

    # delta_i = rowsum(dO_i * O_i): tiny elementwise reduce, XLA fuses it
    delta = jnp.einsum("bhsd,bhsd->bhs", do.astype(jnp.float32),
                       out.astype(jnp.float32))[..., None]

    params = _CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))

    q_spec_i = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    kv_spec_j = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                             memory_space=pltpu.VMEM)
    row_spec_i = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                              memory_space=pltpu.VMEM)

    in_specs = [q_spec_i, kv_spec_j, kv_spec_j, q_spec_i, row_spec_i,
                row_spec_i]
    operands = [q, k, v, do, lse, delta]
    if has_seg:
        qs_spec, ks_spec = _seg_specs(bq, bk)
        in_specs += [qs_spec, ks_spec]
        operands += [q_seg[..., None], kv_seg[..., None]]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, bq=bq, bk=bk, n_kv=n_kv),
        grid=(B, H, n_q, n_kv),
        in_specs=in_specs,
        out_specs=q_spec_i,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=params,
        interpret=_interpret(),
    )(*operands)

    # kv-major grid: q is the reduction (innermost) dim
    q_spec_j = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, j, 0),
                            memory_space=pltpu.VMEM)
    kv_spec_i = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, i, 0),
                             memory_space=pltpu.VMEM)
    row_spec_j = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, j, 0),
                              memory_space=pltpu.VMEM)

    in_specs = [q_spec_j, kv_spec_i, kv_spec_i, q_spec_j, row_spec_j,
                row_spec_j]
    operands = [q, k, v, do, lse, delta]
    if has_seg:
        qs_spec, ks_spec = _seg_specs(bq, bk, q_major=False)
        in_specs += [qs_spec, ks_spec]
        operands += [q_seg[..., None], kv_seg[..., None]]

    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, bq=bq, bk=bk, n_q=n_q),
        grid=(B, H, n_kv, n_q),
        in_specs=in_specs,
        out_specs=[kv_spec_i, kv_spec_i],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=params,
        interpret=_interpret(),
    )(*operands)
    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


# ---------------------------------------------------------------- public op

def _seg_zero_cot(seg):
    from autodist_tpu.kernel.common.variable_utils import zero_cotangent
    return zero_cotangent(seg)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash(q, k, v, q_seg, kv_seg, causal, block_q, block_k):
    segs = None if q_seg is None else (q_seg, kv_seg)
    out, _ = _fwd(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                  v.transpose(0, 2, 1, 3), segs, causal, block_q, block_k)
    return out.transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, q_seg, kv_seg, causal, block_q, block_k):
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    segs = None if q_seg is None else (q_seg, kv_seg)
    out, lse = _fwd(qt, kt, vt, segs, causal, block_q, block_k)
    return out.transpose(0, 2, 1, 3), (qt, kt, vt, out, lse, q_seg, kv_seg)


def _flash_bwd(causal, block_q, block_k, res, do):
    dq, dk, dv = _bwd(causal, block_q, block_k, res, do)
    return dq, dk, dv, _seg_zero_cot(res[5]), _seg_zero_cot(res[6])


_flash.defvjp(_flash_fwd, _flash_bwd)


def _tileable(q, k, block_q, block_k):
    return bool(_pick_block(q.shape[1], block_q)) and \
        bool(_pick_block(k.shape[1], block_k))


def flash_attention(q, k, v, causal: bool = False, segment_ids=None,
                    block_q: int = 128, block_k: int = 128):
    """Exact fused attention. q,k,v: [B, S, H, D] -> [B, S, H, D].

    ``segment_ids``: [B, S] int32 (shared q/kv for self-attention) or a
    ``(q_seg, kv_seg)`` pair — attention is allowed iff the ids are equal.
    For a BERT-style key-padding mask, pass validity as segment ids (1 for
    real tokens, 0 for padding): valid tokens then attend exactly the
    valid tokens; padding rows attend padding (their outputs are excluded
    from any loss that masks padding, which BERT's MLM objective does).
    Composes with ``causal``.

    Falls back to the XLA reference path (differentiable as usual) when the
    sequence can't be tiled (remainder below the 8-row minimum block)."""
    if segment_ids is None:
        q_seg = kv_seg = None
    elif isinstance(segment_ids, (tuple, list)):
        q_seg = jnp.asarray(segment_ids[0], jnp.int32)
        kv_seg = jnp.asarray(segment_ids[1], jnp.int32)
    else:
        q_seg = kv_seg = jnp.asarray(segment_ids, jnp.int32)
    if not _tileable(q, k, block_q, block_k):
        from autodist_tpu.ops.attention import reference_attention
        mask = None
        if causal:
            rows = jnp.arange(q.shape[1])[:, None]
            cols = jnp.arange(k.shape[1])[None, :]
            mask = (rows >= cols)[None, None]
        if q_seg is not None:
            seg_mask = (q_seg[:, :, None] == kv_seg[:, None, :])[:, None]
            mask = seg_mask if mask is None else jnp.logical_and(mask,
                                                                 seg_mask)
        return reference_attention(q, k, v, mask)
    return _flash(q, k, v, q_seg, kv_seg, causal, block_q, block_k)


def make_flash_attn_fn(causal: bool = True, block_q: int = 128,
                       block_k: int = 128):
    """(q, k, v, mask) -> out adapter for model layers' ``attn_fn`` slot.

    A key-padding mask (boolean, broadcastable [B, 1, 1, S] / [B, S])
    becomes segment ids (valid=1, pad=0) — the masked-tile block path.
    Arbitrary dense masks are not expressible as segments and raise."""
    def attn(q, k, v, mask=None):
        if mask is None:
            return flash_attention(q, k, v, causal, None, block_q, block_k)
        m = jnp.asarray(mask)
        # accept [B, S] or the layers' [B, 1, 1, S] broadcast form
        if m.ndim == 4 and m.shape[1] == 1 and m.shape[2] == 1:
            m = m[:, 0, 0, :]
        elif m.ndim != 2:
            raise ValueError(
                "flash attention supports key-padding masks ([B, S] or "
                "[B, 1, 1, S]) via segment ids; got mask shape %s"
                % (mask.shape,))
        seg = m.astype(jnp.int32)
        return flash_attention(q, k, v, causal, seg, block_q, block_k)
    return attn
