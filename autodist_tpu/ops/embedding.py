"""Embedding lookup with a sparse-gradient wire path.

Analog of the reference's sparse synchronization: AllReduce all-gathers
IndexedSlices' indices+values instead of densifying
(reference ``autodist/kernel/synchronization/all_reduce_synchronizer.py:132-173``)
and the PS path ships/splits slices by index range
(reference ``kernel/partitioner.py:660-684``, sparse accumulators
``ps_synchronizer.py:476-535``). JAX has no IndexedSlices — ``jax.grad``
materializes a DENSE cotangent for a gathered table — so the sparse wire
path needs the lowering's cooperation:

**The tap trick.** ``embedding_lookup(table, ids, name=...)`` is an
ordinary ``take`` until the lowering activates a capture context. Then the
lookup computes ``stop_gradient(table)[ids] + tap`` where ``tap`` is a
zeros array shaped like the gathered rows: the table itself receives NO
dense gradient, while ``d loss / d tap`` IS exactly the per-row gradient
values (and ``ids`` is already in hand). The step then synchronizes
``(ids, values)`` — batch-sized — instead of a vocab-sized dense array:

- AllReduce path: all-gather ids+values across the mesh, scatter-add
  locally into the update (wire bytes ~ batch x dim instead of
  vocab x dim);
- host-PS path: ship (ids, values) to the store, which scatter-adds into
  each owner shard's index range on the host (the reference's
  index-range split).

``embedding_lookup`` is the framework's opt-in surface (the reference had
the same property: sparsity flowed only through ``tf.nn.embedding_lookup``
producing IndexedSlices). A sparse-detected variable whose lookups don't
carry a matching ``name`` falls back to dense psum with a warning.
"""
import contextlib
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

_TLS = threading.local()


class SparseCapture:
    """State of one traced step under the capture context.

    ``record=True`` (discovery trace): log each lookup's ids/feature shapes so
    the lowering can build taps. ``record=False`` (the real step): consume
    taps and collect the traced ids for the aux output."""

    def __init__(self, taps: Optional[Dict[str, List]] = None,
                 record: bool = False):
        self.taps = taps or {}
        self.record = record
        self.calls: Dict[str, int] = {}
        self.ids: Dict[str, List] = {}
        # name -> [(ids_shape, ids_dtype_str, feat_shape), ...] per call
        self.shapes: Dict[str, List[Tuple]] = {}


def current_capture() -> Optional[SparseCapture]:
    return getattr(_TLS, "capture", None)


def clear_capture() -> None:
    """Drop any capture context leaked on this thread (an exception can
    escape a trace before ``capture``'s finally restores the previous
    context chain) — called by ``autodist_tpu.reset()``."""
    _TLS.capture = None


@contextlib.contextmanager
def capture(taps: Optional[Dict[str, List]] = None, record: bool = False):
    prev = current_capture()
    cap = SparseCapture(taps, record)
    _TLS.capture = cap
    try:
        yield cap
    finally:
        _TLS.capture = prev


def embedding_lookup(table: jax.Array, ids: jax.Array,
                     name: Optional[str] = None) -> jax.Array:
    """Row lookup ``table[ids]`` with an optional sparse-gradient identity.

    ``name`` must equal the table's flattened parameter name (e.g.
    ``"embed/table"``) for the sparse wire path to engage; without it the
    op is exactly ``jnp.take(table, ids, axis=0)``."""
    cap = current_capture()
    if cap is None or name is None:
        return jnp.take(table, ids, axis=0)
    k = cap.calls.get(name, 0)
    cap.calls[name] = k + 1
    if cap.record:
        cap.shapes.setdefault(name, []).append(
            (tuple(ids.shape), str(ids.dtype), tuple(table.shape[1:]),
             str(table.dtype)))
        return jnp.take(table, ids, axis=0)
    taps = cap.taps.get(name)
    if taps is None or k >= len(taps):
        return jnp.take(table, ids, axis=0)
    cap.ids.setdefault(name, []).append(ids)
    rows = jnp.take(jax.lax.stop_gradient(table), ids, axis=0)
    return rows + taps[k]


def discover(loss_fn, params, example_batch,
             candidate_names) -> Dict[str, List[Tuple]]:
    """Trace the loss once in record mode; return the tap shapes for every
    candidate sparse var that flowed through a named ``embedding_lookup``."""
    # a fresh wrapper defeats JAX's trace cache: the recording side effect
    # must run even when the same loss fn was already traced (sparse
    # detection, metric-spec eval) without the capture context active
    def fresh(p, b):
        return loss_fn(p, b)
    with capture(record=True) as cap:
        jax.eval_shape(fresh, params, example_batch)
    return {n: specs for n, specs in cap.shapes.items()
            if n in candidate_names}


def safe_sparse_names(loss_fn, params, example_batch, specs,
                      param_names) -> set:
    """Subset of discovered sparse vars whose DENSE cotangent is
    structurally zero under tap capture — i.e. the table's only gradient
    path is through the lookups. A table with other differentiable uses
    (tied output embeddings, weight sharing) gets a real dense gradient
    that the sparse wire would silently drop, so those vars must stay on
    the dense path. Checked on the gradient jaxpr: a clean table's grad is
    a broadcast of literal zero."""
    def wrapped(p, taps, b):
        with capture(taps):
            return loss_fn(p, b)

    taps = make_taps(specs)
    closed = jax.make_jaxpr(jax.grad(wrapped, argnums=0))(
        params, taps, example_batch)
    jaxpr = closed.jaxpr
    producers = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            producers[ov] = eqn

    def is_zero(atom, depth=0) -> bool:
        if hasattr(atom, "val"):  # literal
            import numpy as _np
            try:
                return bool((_np.asarray(atom.val) == 0).all())
            except Exception:  # noqa: BLE001
                return False
        eqn = producers.get(atom)
        if eqn is None or depth > 3:
            return False
        if eqn.primitive.name in ("broadcast_in_dim", "convert_element_type"):
            return is_zero(eqn.invars[0], depth + 1)
        return False

    out = set()
    flat_names = param_names
    for i, n in enumerate(flat_names):
        if n not in specs:
            continue
        if is_zero(jaxpr.outvars[i]):
            out.add(n)
    return out


def make_taps(shape_specs: Dict[str, List[Tuple]]) -> Dict[str, List]:
    """Zeros taps matching a discovery result (per lookup call)."""
    return {
        name: [jnp.zeros(tuple(ids_shape) + tuple(feat_shape), feat_dtype)
               for ids_shape, _dt, feat_shape, feat_dtype in specs]
        for name, specs in shape_specs.items()}


def flatten_pairs(ids_list: List, tap_grads: List) -> Tuple[jax.Array, jax.Array]:
    """Merge a var's per-call (ids, values) into one flat pair:
    ids (L,), values (L, feat_elems)."""
    flat_ids, flat_vals = [], []
    for ids, vals in zip(ids_list, tap_grads):
        flat_ids.append(ids.reshape(-1))
        flat_vals.append(vals.reshape(ids.size, -1))
    return jnp.concatenate(flat_ids), jnp.concatenate(flat_vals, axis=0)


def gather_pairs(ids: jax.Array, vals: jax.Array, axis_names) -> Tuple[jax.Array, jax.Array]:
    """All-gather an (ids, values) pair across mesh axes — the sparse wire
    (reference ``all_reduce_synchronizer.py:155-169``). Wire bytes are
    batch-shaped, not vocab-shaped."""
    g_ids = jax.lax.all_gather(ids, axis_names, axis=0, tiled=True)
    g_vals = jax.lax.all_gather(vals, axis_names, axis=0, tiled=True)
    return g_ids, g_vals


def scatter_add_dense(ids: jax.Array, vals: jax.Array, vocab: int,
                      feat_shape: Tuple[int, ...]) -> jax.Array:
    """(ids, values) -> dense gradient (the local densify after the wire)."""
    import math
    feat = math.prod(feat_shape) if feat_shape else 1
    dense = jnp.zeros((vocab, feat), vals.dtype).at[ids].add(vals)
    return dense.reshape((vocab,) + tuple(feat_shape))
