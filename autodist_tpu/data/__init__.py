"""Input pipeline: native record loading + device prefetch.

The TPU-native replacement for the input-pipeline surface the reference
borrows from TensorFlow's C++ runtime (tf.data iterators feeding the
session's feed_dict through the Remapper). Two pieces:

- ``RecordFileWriter`` / ``RecordFileDataset`` — fixed-shape binary record
  files read by the native C++ loader (``native/dataloader/``): mmap'd IO,
  per-epoch shuffling, and batch assembly on C++ threads that never touch
  the GIL, delivering zero-copy numpy views.
- ``DevicePrefetcher`` — wraps any host-batch iterator and keeps the next
  batches' host->device transfers in flight (through the Remapper's
  sharded placement) while the current step computes.
"""
from autodist_tpu.data.record_dataset import RecordFileDataset, RecordFileWriter
from autodist_tpu.data.prefetch import DevicePrefetcher

__all__ = ["RecordFileDataset", "RecordFileWriter", "DevicePrefetcher"]
