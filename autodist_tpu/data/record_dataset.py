"""Fixed-shape record files + the ctypes binding to the native loader.

Format "ADT1" (see ``native/dataloader/dataloader.cc``): a 20-byte header
(magic, n_records, record_bytes) followed by packed fixed-size records; a
``<path>.json`` sidecar describes the per-record field layout (name, dtype,
shape) so batches slice into a dict of numpy arrays with zero copies.
"""
import ctypes
import json
import os
import struct
import subprocess
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.utils import logging

# native sources live inside the package so installed copies can build too
_NATIVE_DIR = os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "native")
_LIB = os.path.join(_NATIVE_DIR, "build", "libadt_dataloader.so")

_MAGIC = b"ADT1"
_HEADER = struct.Struct("<4sQQ")


def build_library(force: bool = False) -> str:
    """Compile the native loader with make (cached), mirroring
    runtime/coordination.py's build-on-demand pattern."""
    src = os.path.join(_NATIVE_DIR, "dataloader", "dataloader.cc")
    if not force and os.path.exists(_LIB) and \
            os.path.getmtime(_LIB) >= os.path.getmtime(src):
        return _LIB
    logging.info("building native dataloader (%s)", src)
    subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                   capture_output=True)
    return _LIB


_DLL = None


def _dll():
    global _DLL
    if _DLL is None:
        dll = ctypes.CDLL(build_library())
        dll.adl_open.restype = ctypes.c_void_p
        dll.adl_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
                                 ctypes.c_uint64]
        dll.adl_open_sharded.restype = ctypes.c_void_p
        dll.adl_open_sharded.argtypes = dll.adl_open.argtypes + [
            ctypes.c_uint64, ctypes.c_uint64]
        dll.adl_next_batch.restype = ctypes.POINTER(ctypes.c_uint8)
        dll.adl_next_batch.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint64)]
        dll.adl_release_batch.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        dll.adl_close.argtypes = [ctypes.c_void_p]
        for f in (dll.adl_record_bytes, dll.adl_num_records,
                  dll.adl_batches_per_epoch):
            f.restype = ctypes.c_uint64
            f.argtypes = [ctypes.c_void_p]
        _DLL = dll
    return _DLL


class _Field:
    def __init__(self, name: str, dtype, shape: Sequence[int]):
        self.name = name
        self.dtype = np.dtype(dtype)
        self.shape = tuple(int(s) for s in shape)
        self.nbytes = int(self.dtype.itemsize * np.prod(self.shape or (1,)))

    def to_dict(self):
        return {"name": self.name, "dtype": self.dtype.str,
                "shape": list(self.shape)}


class RecordFileWriter:
    """Writes an ADT1 record file from dicts of fixed-shape arrays.

    >>> with RecordFileWriter("/tmp/train.adt",
    ...         fields=[("image", np.float32, (32, 32, 3)),
    ...                 ("label", np.int32, ())]) as w:
    ...     for image, label in samples:
    ...         w.write({"image": image, "label": label})
    """

    def __init__(self, path: str, fields: Sequence[Tuple]):
        self.path = path
        self.fields = [_Field(*f) for f in fields]
        self.record_bytes = sum(f.nbytes for f in self.fields)
        self._n = 0
        self._f = open(path, "wb")
        self._f.write(_HEADER.pack(_MAGIC, 0, self.record_bytes))

    def write(self, sample: Dict[str, np.ndarray]):
        buf = bytearray()
        for f in self.fields:
            # asarray, not ascontiguousarray: the latter promotes 0-d
            # scalars to 1-d and would fail the shape check; tobytes()
            # handles non-contiguous inputs itself
            arr = np.asarray(sample[f.name], dtype=f.dtype)
            if arr.shape != f.shape:
                raise ValueError("field %r: shape %s != declared %s"
                                 % (f.name, arr.shape, f.shape))
            buf += arr.tobytes()
        self._f.write(buf)
        self._n += 1

    def write_batch(self, samples: Dict[str, np.ndarray]):
        """Write N records in one call: each field is ``[N, *shape]``.
        Packs through a structured array (packed, no alignment padding) —
        one tobytes() instead of N Python-level write() calls, which
        matters when materializing millions of records (e.g. MovieLens
        interactions)."""
        n = int(np.asarray(samples[self.fields[0].name]).shape[0])
        dt = np.dtype([(f.name, f.dtype, f.shape) for f in self.fields])
        assert dt.itemsize == self.record_bytes
        packed = np.empty(n, dt)
        for f in self.fields:
            arr = np.asarray(samples[f.name], dtype=f.dtype)
            if arr.shape != (n,) + f.shape:
                raise ValueError("field %r: shape %s != %s"
                                 % (f.name, arr.shape, (n,) + f.shape))
            packed[f.name] = arr
        self._f.write(packed.tobytes())
        self._n += n

    def close(self):
        if self._f is None:
            return
        self._f.seek(0)
        self._f.write(_HEADER.pack(_MAGIC, self._n, self.record_bytes))
        self._f.close()
        self._f = None
        with open(self.path + ".json", "w") as f:
            json.dump({"fields": [fl.to_dict() for fl in self.fields],
                       "n_records": self._n}, f, indent=1)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter may be shutting down
            pass


class RecordFileDataset:
    """Infinite shuffled batch stream over an ADT1 file, assembled by the
    native loader's worker threads.

    Batches are dicts of numpy arrays ``[batch, *field_shape]``. By default
    each batch owns its memory (one cheap memcpy out of the native ring
    slot — safe to hold across steps and to hand to async device
    transfers). ``copy=False`` yields zero-copy views into the ring slot,
    valid only until the NEXT ``__next__`` call and only for consumers that
    finish reading the buffer synchronously before advancing.
    """

    def __init__(self, path: str, batch_size: int, shuffle: bool = True,
                 seed: int = 0, num_threads: int = 2, ring_slots: int = 4,
                 copy: bool = True, shard: Tuple[int, int] = (0, 1)):
        """``shard=(index, count)`` restricts this loader to the strided
        record subset {i : i % count == index} — the multi-host input
        pattern: each process loads its OWN disjoint 1/count slice (its
        per-process batch) instead of materializing the global batch
        everywhere; pair with ``Remapper.remap_feed_local``."""
        with open(path + ".json") as f:
            meta = json.load(f)
        self.fields = [_Field(d["name"], d["dtype"], d["shape"])
                       for d in meta["fields"]]
        self.batch_size = int(batch_size)
        self.shard = (int(shard[0]), int(shard[1]))
        self._handle = _dll().adl_open_sharded(
            path.encode(), self.batch_size, int(shuffle), seed, num_threads,
            ring_slots, self.shard[0], self.shard[1])
        if not self._handle:
            raise ValueError("could not open record file %s" % path)
        # SHARD-LOCAL record count: the records THIS loader iterates
        # (i % count == index). Epoch accounting / sampling weights over
        # the whole dataset must use num_records_global instead.
        self.num_records = int(_dll().adl_num_records(self._handle))
        with open(path, "rb") as hf:
            _, self.num_records_global, _ = _HEADER.unpack(
                hf.read(_HEADER.size))
        self.num_records_global = int(self.num_records_global)
        self.batches_per_epoch = int(_dll().adl_batches_per_epoch(self._handle))
        self.record_bytes = int(_dll().adl_record_bytes(self._handle))
        self._copy = copy
        self._pending: Optional[int] = None

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        if self._handle is None:
            raise ValueError("dataset is closed")
        if self._pending is not None:
            _dll().adl_release_batch(self._handle, self._pending)
            self._pending = None
        idx = ctypes.c_uint64()
        ptr = _dll().adl_next_batch(self._handle, ctypes.byref(idx))
        if not ptr:
            raise StopIteration  # closed under our feet
        self._pending = idx.value
        flat = np.ctypeslib.as_array(
            ptr, shape=(self.batch_size * self.record_bytes,))
        batch, off = {}, 0
        # records are packed [record0, record1, ...]; view as
        # [batch, record_bytes] then slice each field's byte range
        rows = flat.reshape(self.batch_size, self.record_bytes)
        for f in self.fields:
            raw = rows[:, off:off + f.nbytes]
            if self._copy:
                # a real owning copy — NOT ascontiguousarray, which is a
                # no-op (aliasing view) when the slice is already contiguous
                raw = raw.copy()
            elif not raw.flags.c_contiguous:
                # zero-copy mode still needs the gather a strided
                # multi-field column requires before viewing as f.dtype
                raw = np.ascontiguousarray(raw)
            batch[f.name] = raw.view(f.dtype).reshape(
                (self.batch_size,) + f.shape)
            off += f.nbytes
        if self._copy:
            _dll().adl_release_batch(self._handle, self._pending)
            self._pending = None
        return batch

    def close(self):
        if self._handle is not None:
            if self._pending is not None:
                _dll().adl_release_batch(self._handle, self._pending)
                self._pending = None
            _dll().adl_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # releases the native worker threads, mmap, and fd when the dataset
        # is dropped without close() (e.g. notebook / per-experiment use)
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter may be shutting down
            pass
