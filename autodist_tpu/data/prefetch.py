"""Device prefetch: overlap host->device transfer with device compute.

The piece of the reference's input pipeline that actually buys steps/s on
TPU: while step N computes, batch N+1 (and N+2, ...) is already being
placed on the mesh. JAX transfers are async, so the prefetcher simply runs
the Remapper's sharded placement ``depth`` batches ahead of consumption —
a transfer queue, no threads needed; the native loader's worker threads
(record_dataset) keep the host side ahead of the transfers.
"""
import collections
from typing import Callable, Iterable, Iterator

import numpy as np

from autodist_tpu.telemetry import spans as tel


def stack_batches(group, pad_to: int = None):
    """Stack a list of same-structure batches into one ``[k, ...]`` feed
    (the fused engine's input shape). Device-resident leaves stack on
    device (``jnp.stack`` — no host round-trip); host leaves via
    ``np.stack``. The ONE stacking rule, shared by
    :class:`DevicePrefetcher`'s stack mode, ``Runner.fit``'s grouping
    path, and the serving micro-batcher.

    ``pad_to=n`` (>= len(group)) PADS the stacked leading dim to ``n`` by
    repeating the last element — the serving path's pad-to-bucket rule
    (a short request group runs on the nearest compiled bucket shape
    instead of recompiling; repeated rows are real data, so no model can
    NaN on them, and the caller masks rows ``>= len(group)`` out of the
    fetches). Training callers keep the default (no padding): a padded
    TRAINING step would silently weight the repeated examples into the
    gradient."""
    import jax
    if not group:
        raise ValueError("stack_batches on an empty group — nothing to "
                         "stack (or pad)")
    if pad_to is not None:
        if pad_to < len(group):
            raise ValueError(
                "stack_batches(pad_to=%d) with %d items — pad_to must be "
                ">= the group size" % (pad_to, len(group)))
        group = list(group) + [group[-1]] * (pad_to - len(group))

    def stack(*ls):
        if isinstance(ls[0], jax.Array):
            if not all(getattr(l, "is_fully_addressable", True)
                       for l in ls):
                # a multi-process global array cannot be re-stacked
                # process-locally; jnp.stack's raw error would not say
                # what to do about it
                raise ValueError(
                    "cannot stack multi-process global arrays into a "
                    "fused [k, ...] feed — feed host numpy batches, or "
                    "pre-stack with DevicePrefetcher(stack=k) so the "
                    "placement happens once, already stacked")
            return jax.numpy.stack(ls)
        return np.stack([np.asarray(l) for l in ls])
    return jax.tree_util.tree_map(stack, *group)


class DevicePrefetcher:
    """Wraps a host-batch iterator; yields device-resident (mesh-sharded)
    batches with ``depth`` placements in flight.

    ``place`` converts one host batch to device form — by default the
    runner's ``remapper.remap_feed`` (pass a Runner), or any callable.

        pf = DevicePrefetcher(dataset, runner, depth=2)
        for batch in pf:                      # already on the mesh
            metrics = runner.run(batch)       # remap_feed is a no-op here

    ``stack=k`` (> 1) is the fused-engine feed mode: k consecutive host
    batches are stacked into ONE ``[k, ...]`` feed and placed via
    ``remapper.remap_feed_stack`` — the whole superstep's data lands in a
    single transfer, issued behind the previous superstep's compute:

        pf = DevicePrefetcher(dataset, runner, depth=2, stack=4)
        runner.fit(pf, fuse_steps=4, metrics_every=8)

    (``fit`` recognizes a matching ``stack_k`` and consumes the items
    whole instead of re-grouping.) A trailing group smaller than k is
    dropped with a warning — a smaller stack would force a recompile of
    the fused program.
    """

    def __init__(self, iterable: Iterable, runner_or_place, depth: int = 2,
                 stack: int = 1):
        if stack < 1:
            raise ValueError("stack must be >= 1")
        self.stack_k = stack
        if callable(runner_or_place):
            # custom placement callable: in stack mode it receives the
            # already-stacked [k, ...] host batch
            self._place: Callable = runner_or_place
        elif stack > 1:
            self._place = runner_or_place.remapper.remap_feed_stack
        else:
            self._place = runner_or_place.remapper.remap_feed
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._depth = depth
        self._it = iter(iterable)
        self._queue = collections.deque()
        self._exhausted = False
        # observable data-loss accounting (stack mode's dropped tails)
        self.dropped_batches = 0
        self.dropped_examples = 0

    def _next_host_item(self):
        """One queue item's host batch: a plain batch, or a [k, ...]
        stacked group in stack mode. Raises StopIteration when done."""
        if self.stack_k == 1:
            return next(self._it)
        group = []
        for _ in range(self.stack_k):
            try:
                group.append(next(self._it))
            except StopIteration:
                break
        if not group:
            raise StopIteration
        if len(group) < self.stack_k:
            # count the DATA cost of the drop, not just the event: the
            # tail's examples never train (once per epoch — the iterator
            # is exhausted exactly once), and the registry exposes the
            # running totals so a multi-epoch job can see the loss rate
            examples = sum(self._batch_examples(b) for b in group)
            self.dropped_batches += len(group)
            self.dropped_examples += examples
            tel.counter_add("prefetch.dropped_batches", len(group))
            tel.counter_add("prefetch.dropped_examples", examples)
            tel.instant("prefetch.dropped_tail", "prefetch",
                        batches=len(group), examples=examples)
            from autodist_tpu.utils import logging
            logging.warning(
                "DevicePrefetcher(stack=%d): dropping trailing group of "
                "%d batch(es) / %d example(s) this epoch — a short stack "
                "would recompile the fused program (totals so far: %d "
                "batches, %d examples)", self.stack_k, len(group),
                examples, self.dropped_batches, self.dropped_examples)
            raise StopIteration
        return stack_batches(group)

    @staticmethod
    def _batch_examples(batch) -> int:
        """Leading-dim example count of one host batch (0 if opaque)."""
        import jax
        for leaf in jax.tree_util.tree_leaves(batch):
            shape = np.shape(leaf)
            if len(shape) >= 1:
                return int(shape[0])
        return 0

    def _fill(self):
        while not self._exhausted and len(self._queue) < self._depth:
            try:
                host_batch = self._next_host_item()
            except StopIteration:
                self._exhausted = True
                return
            # placement is async: this enqueues the transfer and returns
            with tel.span("prefetch.place", "prefetch",
                          stack=self.stack_k):
                self._queue.append(self._place(host_batch))
        # occupancy AFTER filling: 0 here means the consumer is about to
        # stall on the host side — the starvation signal
        tel.gauge_set("prefetch.queue_depth", len(self._queue))

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        self._fill()
        if not self._queue:
            raise StopIteration
        out = self._queue.popleft()
        tel.counter_add("prefetch.batches")
        self._fill()  # immediately start the replacement transfer
        return out

    def take(self, n: int) -> Iterator:
        """Bounded view: yield at most n batches (for infinite datasets)."""
        for _ in range(n):
            try:
                yield next(self)
            except StopIteration:
                return
