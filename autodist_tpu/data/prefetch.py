"""Device prefetch: overlap host->device transfer with device compute.

The piece of the reference's input pipeline that actually buys steps/s on
TPU: while step N computes, batch N+1 (and N+2, ...) is already being
placed on the mesh. JAX transfers are async, so the prefetcher simply runs
the Remapper's sharded placement ``depth`` batches ahead of consumption —
a transfer queue, no threads needed; the native loader's worker threads
(record_dataset) keep the host side ahead of the transfers.
"""
import collections
from typing import Callable, Iterable, Iterator


class DevicePrefetcher:
    """Wraps a host-batch iterator; yields device-resident (mesh-sharded)
    batches with ``depth`` placements in flight.

    ``place`` converts one host batch to device form — by default the
    runner's ``remapper.remap_feed`` (pass a Runner), or any callable.

        pf = DevicePrefetcher(dataset, runner, depth=2)
        for batch in pf:                      # already on the mesh
            metrics = runner.run(batch)       # remap_feed is a no-op here
    """

    def __init__(self, iterable: Iterable, runner_or_place, depth: int = 2):
        if callable(runner_or_place):
            self._place: Callable = runner_or_place
        else:
            self._place = runner_or_place.remapper.remap_feed
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._depth = depth
        self._it = iter(iterable)
        self._queue = collections.deque()
        self._exhausted = False

    def _fill(self):
        while not self._exhausted and len(self._queue) < self._depth:
            try:
                host_batch = next(self._it)
            except StopIteration:
                self._exhausted = True
                return
            # placement is async: this enqueues the transfer and returns
            self._queue.append(self._place(host_batch))

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        self._fill()
        if not self._queue:
            raise StopIteration
        out = self._queue.popleft()
        self._fill()  # immediately start the replacement transfer
        return out

    def take(self, n: int) -> Iterator:
        """Bounded view: yield at most n batches (for infinite datasets)."""
        for _ in range(n):
            try:
                yield next(self)
            except StopIteration:
                return
