"""Real-text corpus -> ADT1 record files.

The bridge between actual datasets and the native C++ record loader
(``native/dataloader/dataloader.cc``): tokenize text files (byte-level —
vocab 256, no external tokenizer dependency) into fixed-length
next-token-prediction windows and write them as ADT1 records that
``RecordFileDataset`` mmaps and batches with shuffling worker threads.

This is the "real data" end of the reference's input pipelines (the
reference feeds lm1b/ImageNet TFRecords through tf.data; here the native
loader is the tf.data analog and this module the dataset-preparation step).
"""
import os
from typing import Iterable, List, Sequence, Tuple

import numpy as np

from autodist_tpu.data.record_dataset import RecordFileWriter

BYTE_VOCAB = 256


def load_text(paths: Sequence[str]) -> bytes:
    """Concatenate text files (sorted for determinism)."""
    chunks: List[bytes] = []
    for p in sorted(paths):
        with open(p, "rb") as f:
            chunks.append(f.read())
    return b"\n".join(chunks)


def byte_windows(data: bytes, seq_len: int, stride: int = 0) -> np.ndarray:
    """Overlapping byte-token windows of length seq_len+1 (inputs+target).
    ``stride`` defaults to ``seq_len`` (non-overlapping)."""
    stride = stride or seq_len
    tokens = np.frombuffer(data, np.uint8).astype(np.int32)
    n = (len(tokens) - seq_len - 1) // stride + 1
    if n <= 0:
        raise ValueError("corpus too small: %d tokens for seq_len %d"
                         % (len(tokens), seq_len))
    idx = np.arange(n)[:, None] * stride + np.arange(seq_len + 1)[None, :]
    return tokens[idx]


def write_lm_records(text_paths: Sequence[str], out_path: str, seq_len: int,
                     stride: int = 0) -> int:
    """Tokenize real text into LM windows and write an ADT1 record file.
    Returns the number of records written."""
    windows = byte_windows(load_text(text_paths), seq_len, stride)
    with RecordFileWriter(out_path,
                          [("tokens", np.int32, (seq_len + 1,))]) as w:
        for row in windows:
            w.write({"tokens": row})
    return int(windows.shape[0])


def repo_docs_corpus(root: str) -> List[str]:
    """The repository's own documentation — a genuinely real English-text
    corpus available offline (README + docs tree)."""
    paths = []
    readme = os.path.join(root, "README.md")
    if os.path.exists(readme):
        paths.append(readme)
    docs = os.path.join(root, "docs")
    for dirpath, _dirs, files in os.walk(docs):
        for f in files:
            if f.endswith((".md", ".rst", ".txt")):
                paths.append(os.path.join(dirpath, f))
    return sorted(paths)
