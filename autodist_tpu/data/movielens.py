"""MovieLens ratings -> NCF training pipeline (parse, split, sample, eval).

TPU-native counterpart of the reference's recommendation stack
(``examples/benchmark/utils/recommendation/``: ``movielens.py`` download/
parse, ``data_preprocessing.py`` id remap + leave-one-out split,
``data_pipeline.py``/``stat_utils.py`` negative sampling,
``neumf_model.py:compute_eval_loss_and_metrics`` HR/NDCG protocol). Design
differences, TPU-first:

- interactions are parsed once into numpy and written as fixed-shape ADT1
  records (``data/record_dataset.py``) so steady-state batches come off
  the NATIVE loader's worker threads, not the Python parser;
- negative sampling is vectorized numpy with rejection against per-user
  positive sets (the reference hashes candidates one at a time in
  ``stat_utils.py``) — a handful of vectorized resample rounds removes
  virtually all false negatives, and the residual count is reported, not
  silently accepted;
- the eval protocol is the standard leave-one-out HR@K / NDCG@K over
  sampled negatives, computed in one batched forward pass per chunk.

The parser accepts the real ``ml-1m``/``ml-10m`` ``ratings.dat`` format
(``user::item::rating::timestamp``) and csv with a header (``ml-25m``).
The repo bundles a SYNTHETIC slice in the same format
(``examples/benchmark/data/ml_tiny_synthetic.dat``) so the pipeline runs
end-to-end in CI with zero egress; point ``load_ratings`` at a real
download for the actual benchmark.
"""
import dataclasses
import os
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from autodist_tpu.utils import logging


@dataclasses.dataclass
class RatingsData:
    """Contiguously re-indexed interactions, time-ordered per user."""
    users: np.ndarray        # int32 [n] in [0, num_users)
    items: np.ndarray        # int32 [n] in [0, num_items)
    timestamps: np.ndarray   # int64 [n]
    num_users: int
    num_items: int

    @property
    def n(self) -> int:
        return int(self.users.shape[0])


def load_ratings(path: str, min_rating: float = 0.0) -> RatingsData:
    """Parse a MovieLens ratings file and remap ids to contiguous ints
    (the reference's ``data_preprocessing.py`` categorical remap).
    ``min_rating`` drops low ratings (the implicit-feedback threshold);
    the default keeps everything, matching the NCF paper's binarization
    of *interactions*."""
    users, items, ratings, stamps = [], [], [], []
    with open(path) as f:
        first = f.readline()
        sep = "::" if "::" in first else ","
        lines = [] if first.lower().startswith("userid") else [first]
        for line in lines + f.readlines():
            line = line.strip()
            if not line:
                continue
            u, i, r, t = line.split(sep)[:4]
            users.append(int(u))
            items.append(int(i))
            ratings.append(float(r))
            stamps.append(int(t))
    users = np.asarray(users, np.int64)
    items = np.asarray(items, np.int64)
    ratings = np.asarray(ratings, np.float32)
    stamps = np.asarray(stamps, np.int64)
    if min_rating > 0:
        keep = ratings >= min_rating
        users, items, stamps = users[keep], items[keep], stamps[keep]
    uniq_u, users = np.unique(users, return_inverse=True)
    uniq_i, items = np.unique(items, return_inverse=True)
    logging.info("movielens: %d interactions, %d users, %d items (%s)",
                 len(users), len(uniq_u), len(uniq_i), os.path.basename(path))
    return RatingsData(users=users.astype(np.int32),
                       items=items.astype(np.int32),
                       timestamps=stamps, num_users=len(uniq_u),
                       num_items=len(uniq_i))


def leave_one_out_split(data: RatingsData) -> Tuple[RatingsData, Dict[int, int]]:
    """The NCF paper's protocol (reference ``data_preprocessing.py``):
    each user's LATEST interaction is held out for evaluation; everything
    else trains. Returns (train split, {user: held-out item})."""
    order = np.lexsort((data.timestamps, data.users))
    u_sorted = data.users[order]
    # last row of each user's time-sorted run = their latest interaction
    is_last = np.r_[u_sorted[1:] != u_sorted[:-1], True]
    test_rows = order[is_last]
    train_rows = order[~is_last]
    holdout = {int(data.users[r]): int(data.items[r]) for r in test_rows}
    train = RatingsData(users=data.users[train_rows],
                        items=data.items[train_rows],
                        timestamps=data.timestamps[train_rows],
                        num_users=data.num_users, num_items=data.num_items)
    return train, holdout


def write_train_records(data: RatingsData, path: str) -> str:
    """Materialize the positive interactions as an ADT1 record file so the
    native loader (C++ worker threads) assembles training batches."""
    from autodist_tpu.data.record_dataset import RecordFileWriter
    with RecordFileWriter(path, fields=[("user", np.int32, ()),
                                        ("item", np.int32, ())]) as w:
        w.write_batch({"user": data.users, "item": data.items})
    return path


class NegativeSampler:
    """Vectorized negative sampling with rejection against each user's
    positive set. One call maps a batch of positive (user, item) pairs to
    the full NCF batch: each positive plus ``neg_per_pos`` sampled
    negatives, labels 1/0."""

    def __init__(self, data: RatingsData, neg_per_pos: int = 4,
                 rounds: int = 4, seed: int = 0):
        self._num_items = data.num_items
        self._neg = neg_per_pos
        self._rounds = rounds
        self._rng = np.random.RandomState(seed)
        # one sorted array of composite (user, item) keys: membership for
        # a whole batch is a single vectorized searchsorted — the data
        # path must never loop in Python per element
        self._keys = np.sort(data.users.astype(np.int64) * data.num_items
                             + data.items)
        self.false_negatives = 0  # residual collisions after all rounds

    def _is_positive(self, users: np.ndarray, items: np.ndarray) -> np.ndarray:
        if not len(self._keys):
            return np.zeros(users.shape, bool)
        keys = users.astype(np.int64) * self._num_items + items
        pos = np.searchsorted(self._keys, keys)
        pos = np.minimum(pos, len(self._keys) - 1)
        return self._keys[pos] == keys

    def batch(self, users: np.ndarray, items: np.ndarray) -> Dict[str, np.ndarray]:
        n = users.shape[0]
        neg_u = np.repeat(users, self._neg)
        neg_i = self._rng.randint(0, self._num_items, neg_u.shape[0])
        for _ in range(self._rounds):
            bad = self._is_positive(neg_u, neg_i)
            if not bad.any():
                break
            neg_i[bad] = self._rng.randint(0, self._num_items,
                                           int(bad.sum()))
        else:
            self.false_negatives += int(self._is_positive(neg_u, neg_i).sum())
        return {
            "user": np.concatenate([users, neg_u]).astype(np.int32),
            "item": np.concatenate([items, neg_i]).astype(np.int32),
            "label": np.concatenate([np.ones(n, np.int32),
                                     np.zeros(neg_u.shape[0], np.int32)]),
        }


def train_batches(record_path: str, data: RatingsData, pos_per_batch: int,
                  neg_per_pos: int = 4, seed: int = 0,
                  num_threads: int = 2) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite NCF batch stream: positives off the NATIVE record loader,
    negatives sampled per batch. Batch size = pos_per_batch x
    (1 + neg_per_pos)."""
    from autodist_tpu.data.record_dataset import RecordFileDataset
    sampler = NegativeSampler(data, neg_per_pos=neg_per_pos, seed=seed)
    ds = RecordFileDataset(record_path, batch_size=pos_per_batch,
                           shuffle=True, seed=seed, num_threads=num_threads)
    try:
        for batch in ds:
            yield sampler.batch(batch["user"], batch["item"])
    finally:
        ds.close()  # abandoned iterators must not leak native threads


def evaluate_hit_ndcg(score_fn, holdout: Dict[int, int], data: RatingsData,
                      num_negatives: int = 99, k: int = 10,
                      seed: int = 0, chunk: int = 256) -> Dict[str, float]:
    """Leave-one-out HR@K / NDCG@K (reference
    ``neumf_model.py:compute_eval_loss_and_metrics``): for each user,
    rank the held-out item against ``num_negatives`` sampled unseen
    items; HR = fraction of users whose held-out item ranks in the top K,
    NDCG discounts by log2(rank+1). ``score_fn(users, items) -> scores``
    is one batched forward pass."""
    rng = np.random.RandomState(seed)
    sampler = NegativeSampler(data, neg_per_pos=num_negatives,
                              seed=seed + 1)
    users = np.asarray(sorted(holdout), np.int32)
    hits, ndcg, false_neg = 0.0, 0.0, 0
    for c0 in range(0, len(users), chunk):
        u = users[c0:c0 + chunk]
        pos = np.asarray([holdout[int(x)] for x in u], np.int32)
        neg_u = np.repeat(u, num_negatives)
        neg_i = rng.randint(0, data.num_items, neg_u.shape[0])
        for _ in range(4):  # negatives must be unseen AND not the held-out
            bad = sampler._is_positive(neg_u, neg_i) | (
                neg_i == np.repeat(pos, num_negatives))
            if not bad.any():
                break
            neg_i[bad] = rng.randint(0, data.num_items, int(bad.sum()))
        else:
            # residual collisions are REPORTED, never silently accepted
            false_neg += int((sampler._is_positive(neg_u, neg_i) | (
                neg_i == np.repeat(pos, num_negatives))).sum())
        all_u = np.concatenate([u, neg_u])
        all_i = np.concatenate([pos, neg_i])
        scores = np.asarray(score_fn(all_u, all_i), np.float32)
        pos_s = scores[:len(u)]
        neg_s = scores[len(u):].reshape(len(u), num_negatives)
        rank = (neg_s > pos_s[:, None]).sum(axis=1)  # 0-based rank
        hits += float((rank < k).sum())
        ndcg += float((np.log(2.0) / np.log(rank + 2.0))[rank < k].sum())
    n = float(len(users))
    return {"hr": hits / n, "ndcg": ndcg / n, "users": int(n),
            "false_negatives": false_neg}
