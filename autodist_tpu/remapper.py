"""Remapper — feed/fetch adaptation between user values and the mesh.

Analog of reference ``autodist/remapper.py:29-313``. The reference splits
each fed batch along its first (polymorphic) dimension across replica
placeholders and maps fetches back (train ops fetched on all replicas,
tensors taken from the master replica or concatenated). Here:

- **feed**: a host-global batch (numpy/pytree) is placed onto the mesh
  sharded along the data axis (``Remapper.remap_feed``); values whose
  leading dim can't shard (scalars) are replicated — the analog of
  "duplicate when no polymorphic dim" (reference ``remapper.py:81-123``).
- **fetch**: step outputs are device-global arrays; replicated metrics come
  back as single host values (the "master replica" read,
  ``remapper.py:125-185``), sharded outputs are gathered and concatenated.
"""
from typing import Any

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.model_item import _normalize_path
from autodist_tpu.utils import logging


class Remapper:
    def __init__(self, mesh, mesh_axis: str, seq_axis: str = None,
                 batch_axes=None, seq_keys=None):
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.seq_axis = seq_axis
        # leaf names whose dim 1 is the sequence dim (strategy
        # graph_config.seq_feed_keys); None = every rank>=2 leaf
        self.seq_keys = frozenset(seq_keys) if seq_keys else None
        # axes the batch dim shards over (expert-parallel strategies add the
        # expert axis so every device sees distinct tokens)
        self.batch_axes = tuple(batch_axes) if batch_axes else (mesh_axis,)
        self.num_replicas = 1
        for a in self.batch_axes:
            self.num_replicas *= int(mesh.shape[a])
        self.seq_shards = mesh.shape[seq_axis] if seq_axis else 1
        # device_put can only retarget arrays onto meshes this process fully
        # owns; multi-process meshes must go through host_to_mesh
        self._fully_addressable = all(
            d.process_index == jax.process_index()
            for d in np.asarray(mesh.devices).flat)

    # ------------------------------------------------------------------ feed

    def _place(self, value, pspec):
        from autodist_tpu.parallel.mesh import host_to_mesh
        return host_to_mesh(self.mesh, value, pspec)

    def _leaf_spec(self, shape, replicas: int, what: str,
                   name: str = None) -> P:
        """PartitionSpec + divisibility validation shared by the global
        and process-local feed paths (``replicas`` is the batch-dim
        divisor the caller needs: all replicas, or this process's).
        With ``seq_keys`` declared, only the named leaves shard dim 1
        over the sequence axis — a one-hot label leaf [B, C] must not
        have its class dim sliced (or spuriously rejected) just for
        being rank 2."""
        if len(shape) == 0:
            return P()
        if shape[0] % replicas != 0:
            raise ValueError(
                "%s batch dim %d is not divisible by the %d replicas; pad "
                "or resize the batch (TPU programs need static, even "
                "shards)" % (what, shape[0], replicas))
        seq_applies = (self.seq_axis and len(shape) >= 2
                       and (self.seq_keys is None or name in self.seq_keys))
        if seq_applies:
            if shape[1] % self.seq_shards != 0:
                raise ValueError(
                    "sequence dim %d of %r is not divisible by the %d "
                    "sequence shards (not a sequence leaf? declare the "
                    "token keys via SequenceParallelAR(seq_keys=[...]))"
                    % (shape[1], name, self.seq_shards))
            return P(self.batch_axes, self.seq_axis)
        return P(self.batch_axes)

    def _place_leaf(self, leaf, spec: P):
        """Place one leaf with ``spec``, passing through leaves already
        mesh-placed with an equivalent sharding — re-placing would
        round-trip them through the host."""
        if isinstance(leaf, jax.Array):
            want = NamedSharding(self.mesh, spec)
            if leaf.sharding.is_equivalent_to(want, leaf.ndim):
                return leaf
            if self._fully_addressable:
                return jax.device_put(leaf, want)
            if not leaf.is_fully_addressable:
                # a multi-process global array with the WRONG sharding
                # cannot be read back host-side (np.asarray raises on
                # non-addressable shards) — tell the caller what to do
                raise ValueError(
                    "feed %s is a multi-process global array with "
                    "sharding %s (want %s); feed host numpy arrays, or "
                    "pre-place with Remapper.remap_feed's target "
                    "sharding" % (np.shape(leaf), leaf.sharding, want))
            # process-local device array: re-place via the host-global
            # path (make_array_from_callback), which every process runs
        return self._place(np.asarray(leaf), spec)

    def remap_feed(self, batch) -> Any:
        """Split the global batch across replicas along dim 0. Leaves that
        are already mesh-placed with the right sharding (e.g. by
        ``data.DevicePrefetcher``) pass through untouched — re-placing
        would round-trip them through the host."""
        def place(path, leaf):
            spec = self._leaf_spec(np.shape(leaf), self.num_replicas,
                                   "global", _normalize_path(path))
            return self._place_leaf(leaf, spec)
        return jax.tree_util.tree_map_with_path(place, batch)

    def remap_feed_stack(self, stacked_batch) -> Any:
        """Place a STACKED ``[k, ...]`` batch for the fused multi-step
        engine: dim 0 is the microstep (scan) dim, kept unsharded; the
        ORIGINAL leaf layout — batch split over the data axes, sequence
        dim over the sequence axis — applies from dim 1 on. One transfer
        feeds k microsteps. Pre-placed leaves (``DevicePrefetcher``'s
        stack mode) pass through untouched."""
        def place(path, leaf):
            shape = np.shape(leaf)
            if len(shape) == 0:
                raise ValueError(
                    "stacked feed %r is a scalar — every leaf needs the "
                    "leading [k] microstep dim" % _normalize_path(path))
            inner = self._leaf_spec(shape[1:], self.num_replicas,
                                    "stacked global", _normalize_path(path))
            return self._place_leaf(leaf, P(None, *inner))
        return jax.tree_util.tree_map_with_path(place, stacked_batch)

    def remap_feed_local(self, local_batch) -> Any:
        """Place a PROCESS-LOCAL batch as this process's slice of the
        global batch — the scalable multi-host feed: each process loads
        only its own 1/process_count of the data (e.g.
        ``RecordFileDataset(shard=(process_index, process_count))``)
        instead of materializing the identical global batch everywhere,
        and the slices concatenate along dim 0 in process order. The
        result is mesh-placed, so ``run``/``remap_feed`` pass it through
        untouched. Single-process jobs: identical to ``remap_feed``."""
        if jax.process_count() == 1:
            return self.remap_feed(local_batch)
        if self.num_replicas % jax.process_count() != 0:
            raise ValueError(
                "cannot feed process-local batches: the %d batch replicas "
                "do not divide evenly over %d processes (each process must "
                "own a whole number of replicas)"
                % (self.num_replicas, jax.process_count()))
        local_replicas = self.num_replicas // jax.process_count()

        def place(path, leaf):
            arr = np.asarray(leaf)
            if arr.ndim == 0:
                # scalars are replicated; every process must provide the
                # same value (cannot be a per-process slice)
                return self._place(arr, P())
            spec = self._leaf_spec(arr.shape, local_replicas, "local",
                                   _normalize_path(path))
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, spec), arr)
        return jax.tree_util.tree_map_with_path(place, local_batch)

    # ----------------------------------------------------------------- fetch

    def remap_fetch(self, fetched) -> Any:
        """Bring step outputs to host: replicated values as scalars/arrays,
        sharded values gathered (concatenated along their sharded dim)."""
        return jax.device_get(fetched)
