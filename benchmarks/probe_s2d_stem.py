"""Exact space-to-depth stem: mathematically identical to conv7x7s2."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import jax, numpy as np
jax.config.update("jax_compilation_cache_dir", "/tmp/adt_jax_cache")
import jax.numpy as jnp

B = 256
rng = np.random.RandomState(0)
x = jnp.asarray(rng.randn(B, 224, 224, 3), jnp.bfloat16)
W = jnp.asarray(rng.randn(7, 7, 3, 64) * 0.05, jnp.bfloat16)

def conv_ref(x, W):
    return jax.lax.conv_general_dilated(
        x, W, (2, 2), [(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))

def s2d(x, b=2):
    B_, H, Wd, C = x.shape
    x = x.reshape(B_, H // b, b, Wd // b, b, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B_, H // b, Wd // b, b * b * C)

def make_w2(W):
    # zero-pad 7x7 -> 8x8 so it aligns to 2x2 blocks with the pad-3 offset:
    # out[i,j] = sum_{ky,kx} x[2i-3+ky, 2j-3+kx] W[ky,kx]
    # let u = 2i-4+p (p=0..7), i.e. pad one leading zero row/col: ky = p-1
    Wp = jnp.zeros((8, 8, 3, 64), W.dtype).at[1:, 1:].set(W)
    # u = 2(i-2+dj)+o with dj=0..3, o=0..1 -> W2[dj,di,(o_r,o_c,c)]
    W2 = Wp.reshape(4, 2, 4, 2, 3, 64)      # [djr, or, djc, oc, c, f]
    W2 = W2.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 12, 64)
    return W2

def conv_s2d(x, W2):
    xs = s2d(x, 2)  # [B,112,112,12], channel order (or, oc, c)
    return jax.lax.conv_general_dilated(
        xs, W2, (1, 1), [(2, 1), (2, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))

W2 = make_w2(W)
a = conv_ref(x[:2], W)
b_ = conv_s2d(x[:2], W2)
print("shapes", a.shape, b_.shape)
err = float(jnp.abs(a.astype(jnp.float32) - b_.astype(jnp.float32)).max())
print("max abs err:", err)

def _sync(r):
    float(jnp.sum(jax.tree_util.tree_leaves(r)[0].astype(jnp.float32)))

def timeit(f, *args, steps=10):
    _sync(f(*args))
    t0 = time.perf_counter()
    for _ in range(steps):
        r = f(*args)
    _sync(r)
    return (time.perf_counter() - t0) / steps

# fwd+bwd like training
g_ref = jax.jit(jax.grad(lambda w, xx: jnp.sum(conv_ref(xx, w).astype(jnp.float32) ** 2)))
g_s2d = jax.jit(jax.grad(lambda w, xx: jnp.sum(conv_s2d(xx, w).astype(jnp.float32) ** 2)))
t_ref = timeit(g_ref, W, x)
t_s2d = timeit(g_s2d, W2, x)
print("stem fwd+bwd: ref %.1f ms   s2d %.1f ms   speedup %.2fx"
      % (t_ref * 1e3, t_s2d * 1e3, t_ref / t_s2d))
