"""Per-stage attribution of the resnet50 train step on the chip."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import jax, numpy as np
jax.config.update("jax_compilation_cache_dir", "/tmp/adt_jax_cache")
import jax.numpy as jnp
import flax.linen as nn
from functools import partial
from autodist_tpu.models import resnet

B = 256
PEAK = 197e12  # bf16 TFLOP/s v5e

def _sync(r):
    # VALUE READBACK: on this tunnel transport block_until_ready can
    # acknowledge before execution drains (see BENCHMARKS.md header)
    leaf = jax.tree_util.tree_leaves(r)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))


def timeit(f, *args, steps=6):
    _sync(f(*args))
    t0 = time.perf_counter()
    for _ in range(steps):
        r = f(*args)
    _sync(r)
    return (time.perf_counter() - t0) / steps

def flops_of(f, *args):
    return jax.jit(f).lower(*args).compile().cost_analysis()["flops"]

rng = np.random.RandomState(0)

# full train step (fwd+bwd via grad of mean-logit loss)
def seg_grad(mod, shape):
    x = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    v = jax.jit(lambda r, xx: mod.init(r, xx, train=False))(jax.random.PRNGKey(0), x[:1])
    def loss(p, xx):
        return jnp.mean(mod.apply(p, xx, train=False) ** 2)
    g = jax.jit(jax.grad(loss))
    dt = timeit(g, v, x)
    fl = flops_of(jax.grad(loss), v, x)
    return dt, fl

class Stem(nn.Module):
    dtype = jnp.bfloat16
    @nn.compact
    def __call__(self, x, train=False):
        x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=jnp.bfloat16)(x.astype(jnp.bfloat16))
        x = nn.BatchNorm(use_running_average=True, dtype=jnp.float32)(x)
        x = nn.relu(x)
        return nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

class Stage(nn.Module):
    filters: int
    count: int
    first_stride: int
    @nn.compact
    def __call__(self, x, train=False):
        for j in range(self.count):
            s = (self.first_stride, self.first_stride) if j == 0 else (1, 1)
            x = resnet.BottleneckBlock(self.filters, s, dtype=jnp.bfloat16)(x, train)
        return x

parts = [
    ("stem 7x7s2+pool", Stem(), (B, 224, 224, 3)),
    ("stage1 64f x3 @56px", Stage(64, 3, 1), (B, 56, 56, 64)),
    ("stage2 128f x4 @56px", Stage(128, 4, 2), (B, 56, 56, 256)),
    ("stage3 256f x6 @28px", Stage(256, 6, 2), (B, 28, 28, 512)),
    ("stage4 512f x3 @14px", Stage(512, 3, 2), (B, 14, 14, 1024)),
]
total_dt = 0.0
for name, mod, shape in parts:
    dt, fl = seg_grad(mod, shape)
    total_dt += dt
    print("%-22s %7.1f ms  %6.2f TFLOP  %5.1f TFLOP/s  mfu %.2f"
          % (name, dt * 1e3, fl / 1e12, fl / dt / 1e12, fl / dt / PEAK),
          flush=True)

# whole model for comparison
lf, params, batch, _ = resnet.make_train_setup(batch_size=B)
g = jax.jit(jax.grad(lf))
dt = timeit(g, params, batch)
fl = flops_of(jax.grad(lf), params, batch)
print("%-22s %7.1f ms  %6.2f TFLOP  %5.1f TFLOP/s  mfu %.2f  (sum of parts %.1f ms)"
      % ("FULL resnet50 step", dt * 1e3, fl / 1e12, fl / dt / 1e12,
         fl / dt / PEAK, total_dt * 1e3), flush=True)
